"""Benchmark: GBDT training throughput on the real chip, multiple workloads.

Artifact contract (un-losable by design): a parseable JSON line with
{"metric", "value", "unit", "vs_baseline", "workloads"} is printed and
flushed after EVERY completed workload — the last line on stdout is always
the most complete snapshot, so a driver timeout mid-run still captures
everything measured so far.  That incremental emission is the primary
guarantee; a SIGTERM/SIGALRM handler additionally emits a final snapshot
when Python-level code is running (signals are deferred while blocked
inside a C call, e.g. a hung remote compile — in that case the
already-printed lines are what survives), and a global wall-clock budget
(BENCH_BUDGET_S, default 840 s) skips not-yet-started workloads as
{"skipped": "budget"} rather than losing the artifact.

Ordering is value-first under the budget: (0) a <90 s smoke that executes
the real Pallas histogram kernel AND one real grow_tree_fast call
(float + int8-quantized), checksummed — closes the eval_shape-only CI
hole for both the kernel and the grower integration around it, (1) the
headline Higgs-like binary workload at the device-recommended max_bin=63
(accuracy parity measured in docs/PERF_NOTES.md: AUC 0.93757 @63 vs
0.93735 @255), (2) the Epsilon-class wide shape at 255 bins — the
BASELINE.json workload that stresses the histogram kernel; its
400k x 2000 host binning (~7 min) is pre-cached via Dataset.save_binary
under .bench_cache/ (if the cache is missing the workload generates +
bins inline only when >420 s of budget remain), (3) the
reference-default max_bin=255 narrow configuration — then (4) LambdaRank
and (5) multiclass, which have no baseline anchor and are first to fall
off the budget.  A persistent XLA compilation cache
(.bench_cache/jaxcache) is enabled at startup; warmups shrink ~2.4x once
a prior process has populated it.

Baseline anchor (BASELINE.md, LOW CONFIDENCE until the reference mount is
populated): reference CPU training of Higgs 10.5M x 28 runs 500 boosting
iterations in ~240 s => ~2.08 iters/sec.  vs_baseline = our iters/sec
linearly scaled to 10.5M rows / 2.08.  Workloads without a published
reference number carry vs_baseline: null.

Env knobs: BENCH_ROWS, BENCH_ITERS, BENCH_MAX_BIN (primary workload),
BENCH_FAST=1 (smoke + primary only), BENCH_BUDGET_S (global budget).

Predict mode (round 9): BENCH_MODE=predict runs the serving benchmark
instead (benchmarks/predict_bench.py — cold compile, warm rows/sec,
p50/p99 batch latency over batch sizes x ensemble sizes) and emits a
{"metric": "predict_rows_per_sec*", ...} artifact row with the same
incremental un-losable contract; its knobs are PREDICT_BENCH_*.

Multislice mode (round 20): BENCH_MODE=multislice runs the hierarchical
two-level-merge dryrun (2 slices x 4 ranks off-chip via the hermetic
subprocess helper; MULTISLICE_SLICES/MULTISLICE_RANKS override): tree ==
single-mesh sharded at full top-k coverage, per-rank round budget, and
the statically pinned per-round DCN byte bill in-artifact
(MULTICHIP_r07-format JSON).

Feature2d mode (round 24): BENCH_MODE=feature2d runs the 2-D
(rows x features) windowed-round dryrun (2x4 float and 4x2 int8
off-chip via the hermetic subprocess helper; FEATURE2D_ROW_SHARDS /
FEATURE2D_FEATURE_SHARDS override the float grid): tree == serial
windowed, per-rank round budget, and the statically pinned per-axis
collective byte bills — the feature axis carrying ONLY the go/no-go
broadcast + election, never histograms — in-artifact
(MULTICHIP_r08-format JSON).

Out-of-core mode (round 12): BENCH_MODE=ooc runs the data-path levers
(benchmarks/ooc_bench.py — stream-ingest rows/s vs chunk size,
spill-training rows/s with bitwise parity asserted, and the partition
move-phase timing at segment fractions that the HBM-resident DMA kernel
must flatten on chip); knobs OOC_BENCH_*.

Serve mode (round 18): BENCH_MODE=serve runs the serving-LOOP benchmark
(benchmarks/serve_bench.py — K concurrent callers coalesced onto one
warm executable vs per-request serial predicts, closed + open loop,
bitwise parity and the jaxpr-audit verdict asserted in-artifact; round
23 adds the `fleet_chaos` row: a 2-replica ServingFleet losing one
replica to an injected death mid-open-loop with zero lost requests,
bitwise parity, and the requeue/restart counts in the artifact);
knobs SERVE_BENCH_*.

Continual mode (round 19): BENCH_MODE=continual runs the train-while-
serving loop benchmark (benchmarks/continual_bench.py — streaming
ingest rows/s incl. the durable CRC'd cache append, refit vs
append-trees update latency, and serve p50/p99 ACROSS zero-downtime
rollovers vs the BENCH_serve_r01 baseline, rollover parity + audit
verdict asserted in-artifact); knobs CONTINUAL_BENCH_*.

Fleet mode (round 21): BENCH_MODE=fleet runs the booster-fleet
benchmark (benchmarks/fleet_bench.py — models/s at B in {1, 64, 4096}
training B independent boosters as one donated dispatch per round via
lgb.train_fleet vs the host loop over the solo windowed grower, with
B=8 bitwise parity float + int8, the warm 1-dispatch/0-sync/0-retrace
round budget pinned per B from the fleet_round event ledger, and the
audit verdict in-artifact); knobs FLEET_BENCH_*.
"""

import json
import os
import signal
import sys
import time

import numpy as np

_BASELINE_IPS = 500.0 / 240.0  # reference CPU Higgs anchor (BASELINE.md)

_T0 = time.monotonic()
# 840 s default.  Round-4 demonstrated the driver tolerates >= 610 s
# (rc=0 at 610.2); beyond that is unknown — but the artifact is emitted
# INCREMENTALLY after every workload, so even a driver kill mid-run
# preserves every completed row (the last stdout line is always a full
# snapshot).  A generous budget therefore only ADDS rows; the r5 warmup
# reality (primary compile ~240 s, epsilon quantized compile ~280 s)
# makes 560 s structurally too small to ever reach the Epsilon row.
_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", 840))

# mutable artifact state: emit() prints a full snapshot of this at any time
_STATE = {
    "metric": "boosting_iters_per_sec",
    "value": None,
    "unit": "iters/sec",
    "vs_baseline": None,
    "workloads": {},
}


def _emit():
    try:
        # embed the telemetry snapshot (docs/OBSERVABILITY.md) so on-chip
        # rows land with dispatch/compile/W-ladder context attached; obs is
        # stdlib-only, so this never forces a jax import
        from lightgbm_tpu.obs import metrics as _obs

        _STATE["metrics"] = _obs.snapshot()
    except Exception:  # noqa: BLE001 — artifact robustness first
        pass
    line = json.dumps(_STATE, default=str) + "\n"
    sys.stdout.write(line)
    sys.stdout.flush()


def _emit_raw():
    """Signal-handler-safe emission: bypass buffered stdout.  The leading
    newline terminates any partially flushed line the signal interrupted,
    so this snapshot always starts (and ends) a clean line."""
    try:
        os.write(1, ("\n" + json.dumps(_STATE) + "\n").encode())
    except Exception:
        pass


def _on_term(signum, frame):  # noqa: ARG001 - signal signature
    _STATE["interrupted"] = {
        "signal": signum, "elapsed_s": round(time.monotonic() - _T0, 1)}
    _emit_raw()
    os._exit(128 + signum)


signal.signal(signal.SIGTERM, _on_term)
signal.signal(signal.SIGINT, _on_term)


class _BudgetExceeded(Exception):
    pass


def _on_alarm(signum, frame):  # noqa: ARG001
    raise _BudgetExceeded()


signal.signal(signal.SIGALRM, _on_alarm)


def _remaining():
    return _BUDGET_S - (time.monotonic() - _T0)


def _run(params, X, y, group=None, iters=30, repeats=1):
    """Train `iters` timed iterations; returns (iters/sec, warmup_s, rates).

    Sync is a HOST PULL of a score slice, not block_until_ready — the axon
    tunnel's block_until_ready returns before the async pipeline drains
    (docs/PERF_NOTES.md round-4 methodology note), so these numbers are
    slightly lower but honest vs the r1-r4 artifacts.  `repeats` re-times
    the same booster to expose run-to-run variance (VERDICT r4 weak #7).

    Phases run under timed_section so every artifact row carries the
    per-section split (binning vs warmup-compile vs steady-state) via
    _sections(), not just the embedded whole-process snapshot — the
    round-10 follow-up from docs/NEXT.md.  The section close is honest:
    each phase ends in the host pull above, and timed_section's tally is
    host wall clock around it."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.profiling import timed_section

    with timed_section("bench_dataset_bin"):
        ds = lgb.Dataset(X, label=y, group=group)
        ds.construct()
    t0 = time.perf_counter()
    with timed_section("bench_warmup"):
        bst = lgb.Booster(params=params, train_set=ds)
        bst.update()
        _ = np.asarray(bst._gbdt._score[:8])
    warmup = time.perf_counter() - t0
    rates = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        with timed_section("bench_train_iters"):
            for _ in range(iters):
                bst.update()
            _ = np.asarray(bst._gbdt._score[:8])
        rates.append(iters / (time.perf_counter() - t0))
    return float(np.median(rates)), warmup, rates


def _sections():
    """Drain the section_seconds tallies accumulated since the last call
    into a {section: {sum_s, count}} dict for the workload's artifact row
    (per-workload attribution needs the reset; the cumulative view stays
    in the embedded metrics snapshot's history)."""
    try:
        from lightgbm_tpu.obs import metrics as _obs

        out = {}
        for name, h in _obs.histogram_items(_obs.SECTION_PREFIX).items():
            out[name[len(_obs.SECTION_PREFIX):]] = {
                "sum_s": round(h.total, 4), "count": h.count}
        _obs.clear_prefix(_obs.SECTION_PREFIX)
        return out
    except Exception:  # noqa: BLE001 — artifact robustness first
        return {}


def _record(name, ips, warmup, vs=None, extra=None):
    entry = {"iters_per_sec": round(ips, 3), "warmup_s": round(warmup, 1),
             "vs_baseline": vs if vs is None else round(vs, 3),
             "sections": _sections()}
    if extra:
        entry.update(extra)
    _STATE["workloads"][name] = entry
    return entry


def _guarded(name, fn, budget_floor=15.0):
    """Run one workload inside the global budget.

    Skips (recording {"skipped": "budget"}) if less than `budget_floor`
    seconds remain; arms SIGALRM for the remaining budget as a best-effort
    over-run stop (it fires between Python bytecodes — a call truly stuck
    inside C is only bounded by the driver's own timeout, against which
    the incremental per-workload emission preserves the artifact); any
    other failure (e.g. transient remote-compile error) records an error
    entry instead of killing the whole run.  Emits a fresh artifact
    snapshot after every outcome.
    """
    rem = _remaining()
    if rem < budget_floor:
        _STATE["workloads"][name] = {"skipped": "budget"}
        _emit()
        return
    try:
        try:
            signal.alarm(max(int(rem), 1))
            fn()
        finally:
            # a late alarm can still fire here before alarm(0) runs — the
            # outer except absorbs it (and the unconditional alarm(0) below
            # covers the skipped disarm)
            signal.alarm(0)
    except _BudgetExceeded:
        # keep an entry fn() already recorded (the alarm may land between
        # the measurement and the return) — only mark error if none exists
        _STATE["workloads"].setdefault(
            name, {"error": "budget exceeded mid-workload"})
    except Exception as e:  # noqa: BLE001 - artifact robustness
        _STATE["workloads"][name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    signal.alarm(0)
    _emit()


def _pallas_smoke():
    """Execute the real Pallas histogram kernel on-chip at a tiny shape and
    checksum it against numpy (VERDICT r3 weak #6: CI only eval_shapes the
    Pallas path; this guarantees one real kernel execution per round)."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops.hist_pallas import histogram_pallas_multi

    n, f, b, tile = 16384, 28, 256, 4
    rng = np.random.RandomState(7)
    bins = rng.randint(0, b, size=(n, f)).astype(np.int16)
    # gradients LEARNABLE from the bins (a tree partitioned on feature 0/1
    # explains most variance) so the grower checksum's correlation bar is
    # reachable; pure-noise g would cap a 7-leaf tree's corr near 0.07
    g = ((bins[:, 0].astype(np.float32) / b - 0.5) * 2.0
         + 0.5 * (bins[:, 1].astype(np.float32) / b - 0.5)
         + 0.1 * rng.randn(n).astype(np.float32))
    h = np.abs(rng.randn(n)).astype(np.float32) + 0.1
    leaf = rng.randint(0, tile, size=n).astype(np.int32)
    mask = np.ones(n, bool)

    t0 = time.perf_counter()
    out = histogram_pallas_multi(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(mask), jnp.asarray(leaf), 0, tile, b)
    out = np.asarray(jax.block_until_ready(out))
    elapsed = time.perf_counter() - t0

    # numpy oracle for slot 0 / feature 0 (out is channel-first (L, 3, F, B))
    ref = np.zeros((b, 3))
    sel = leaf == 0
    np.add.at(ref, bins[sel, 0], np.stack([g[sel], h[sel],
                                           np.ones(sel.sum())], axis=1))
    ok = bool(np.allclose(out[0, 0, 0, :], ref[:, 0], atol=1e-2)
              and np.allclose(out[0, 2, 0, :], ref[:, 2], atol=0.5))

    # one real grow_tree_fast call per path (float + int8) at a tiny shape:
    # catches grower-integration breakage (the r3 NameError class) in the
    # artifact itself, not just the kernel (VERDICT r4 item 7).  256 bins
    # so the Pallas kernel branch (not the XLA einsum) is the one driven.
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.ops.treegrow_fast import grow_tree_fast

    gt0 = time.perf_counter()
    tree_ok = {}
    for tag, q in (("float", 0), ("quant", 16)):
        t, lid = grow_tree_fast(
            jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
            jnp.asarray(mask), jnp.ones((n,), jnp.float32),
            jnp.ones((f,), bool), jnp.full((f,), b, jnp.int32),
            jnp.full((f,), -1, jnp.int32),
            quant_key=jax.random.PRNGKey(0) if q else None,
            num_leaves=7, num_bins=b, params=SplitParams(), leaf_tile=4,
            use_pallas=True, quantize_bins=q, stochastic_rounding=False,
        )
        nl = int(t.num_leaves)
        lv = np.asarray(t.leaf_value[:nl])
        # checksum: the tree fits -grad (g is bins-derived above, so a
        # 7-leaf split on feature 0 must correlate strongly)
        pred = np.asarray(t.leaf_value)[np.asarray(lid)]
        corr = float(np.corrcoef(pred, -g)[0, 1]) if nl > 1 else 0.0
        tree_ok[tag] = bool(nl > 1 and np.isfinite(lv).all() and corr > 0.3)
    grower_s = time.perf_counter() - gt0

    # traced-op count of the grower round body at the primary config: the
    # r5 warmup regression (~137 s -> ~240 s fused-step compile) made
    # trace size a first-class artifact metric — a jump here flags the
    # next compile-time regression off-chip, before it costs a 4-minute
    # tunnel warmup (benchmarks/probe_trace_ops.py has the breakdown)
    from benchmarks.probe_trace_ops import fast_grower_eqns

    trace_eqns = fast_grower_eqns(n=4096, f=f, num_leaves=31,
                                  num_bins=64, leaf_tile=8)

    _STATE["workloads"]["pallas_smoke"] = {
        "ok": ok, "kernel_s": round(elapsed, 1),
        "grower_float_ok": tree_ok["float"],
        "grower_quant_ok": tree_ok["quant"],
        "grower_s": round(grower_s, 1),
        "trace_eqns": trace_eqns,
        "platform": jax.devices()[0].platform}
    if not (ok and all(tree_ok.values())):
        # surface the miscomputation as a hard error entry too (_guarded
        # rewrites this workload's entry), not just a nested flag
        raise AssertionError(
            f"smoke checksum FAILED (kernel={ok}, grower={tree_ok}) on "
            f"{jax.devices()[0].platform}")


def main():
    if os.environ.get("BENCH_MODE") == "predict":
        # serving benchmark: inference throughput/latency instead of
        # training iters/sec (BENCH_predict_* artifact row)
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from benchmarks.predict_bench import main as predict_main

        return predict_main()
    if os.environ.get("BENCH_MODE") == "serve":
        # serving-loop benchmark (round 18): coalesced concurrent
        # requests vs per-request serial predicts, closed + open loop,
        # parity + audit verdict in-artifact (BENCH_serve_* row)
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from benchmarks.serve_bench import main as serve_main

        return serve_main()
    if os.environ.get("BENCH_MODE") == "continual":
        # continual-training loop (round 19): streaming ingest rows/s,
        # refit vs append update latency, serve p50/p99 ACROSS rollovers
        # vs the BENCH_serve_r01 baseline, with in-artifact parity +
        # audit verdict (BENCH_continual_* row)
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from benchmarks.continual_bench import main as continual_main

        return continual_main()
    if os.environ.get("BENCH_MODE") == "fleet":
        # booster-fleet benchmark (round 21): B independent boosters as
        # ONE donated dispatch per round vs the host loop over the solo
        # grower, bitwise parity + per-B round budget + audit verdict
        # in-artifact (BENCH_fleet_* row)
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from benchmarks.fleet_bench import main as fleet_main

        return fleet_main()
    if os.environ.get("BENCH_MODE") == "ooc":
        # out-of-core/partition data-path levers (BENCH_ooc_* artifact)
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from benchmarks.ooc_bench import main as ooc_main

        return ooc_main()
    if os.environ.get("BENCH_MODE") == "multichip":
        # sharded fused windowed dryrun (round 14): the one-dispatch
        # windowed round under shard_map with the histogram merge an
        # in-dispatch psum / psum_scatter, validated for tree equality +
        # the per-rank round budget on an n-device mesh (off-chip this is
        # the CPU loopback mesh; on a slice the same lever exercises real
        # ICI).  Writes MULTICHIP_r06-format JSON to stdout.
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import __graft_entry__ as _ge

        n_dev = int(os.environ.get("MULTICHIP_DEVICES", "8"))
        result = {"n_devices": n_dev, "mode": "sharded_fused_windowed",
                  "merges": {}, "ok": True}
        for merge in ("psum", "scatter"):
            import io
            from contextlib import redirect_stdout

            buf = io.StringIO()
            try:
                with redirect_stdout(buf):
                    _ge.dryrun_multichip_windowed(n_dev, merge)
                result["merges"][merge] = {
                    "rc": 0, "ok": True,
                    "tail": buf.getvalue()[-500:]}
            except Exception as e:  # noqa: BLE001 — artifact robustness
                result["merges"][merge] = {
                    "rc": 1, "ok": False,
                    "tail": (buf.getvalue() + f"\n{type(e).__name__}: "
                             f"{e}")[-800:]}
                result["ok"] = False
        print(json.dumps(result, indent=2))
        return 0 if result["ok"] else 1
    if os.environ.get("BENCH_MODE") == "multislice":
        # hierarchical two-level merge dryrun (round 20): the windowed
        # round over a nested (dcn, ici) mesh — intra-slice
        # psum/psum_scatter unchanged, top-k feature exchange over dcn —
        # validated for tree equality vs the single-mesh sharded round
        # at full top-k coverage + the per-rank round budget, with the
        # statically pinned per-round DCN byte bill from the jaxpr audit
        # embedded in-artifact.  Writes MULTICHIP_r07-format JSON.
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import __graft_entry__ as _ge

        n_slices = int(os.environ.get("MULTISLICE_SLICES", "2"))
        n_ranks = int(os.environ.get("MULTISLICE_RANKS", "4"))
        result = {"num_slices": n_slices, "ranks_per_slice": n_ranks,
                  "mode": "hierarchical_two_level_merge",
                  "merges": {}, "ok": True}
        for merge in ("psum", "scatter"):
            import io
            from contextlib import redirect_stdout

            buf = io.StringIO()
            try:
                with redirect_stdout(buf):
                    _ge.dryrun_multislice_windowed(n_slices, n_ranks, merge)
                result["merges"][merge] = {
                    "rc": 0, "ok": True,
                    "tail": buf.getvalue()[-500:]}
            except Exception as e:  # noqa: BLE001 — artifact robustness
                result["merges"][merge] = {
                    "rc": 1, "ok": False,
                    "tail": (buf.getvalue() + f"\n{type(e).__name__}: "
                             f"{e}")[-800:]}
                result["ok"] = False
        # the DCN byte budget, proven on the traced IR: per-contract
        # dcn_bytes + the collective token sequences ride the artifact
        try:
            from lightgbm_tpu.analysis.jaxpr_audit import run_jaxpr_audit

            rep = run_jaxpr_audit(
                ["windowed_round_hierarchical_psum",
                 "windowed_round_hierarchical_voting"], runtime=False)
            result["jaxpr_audit"] = {
                r.name: {"ok": r.ok,
                         "dcn_bytes": r.detail.get("dcn_bytes"),
                         "large_collectives":
                             r.detail.get("large_collectives")}
                for r in rep.results}
            result["ok"] = result["ok"] and rep.ok
        except Exception as e:  # noqa: BLE001 — artifact robustness
            result["jaxpr_audit"] = {"error": f"{type(e).__name__}: {e}"}
            result["ok"] = False
        print(json.dumps(result, indent=2))
        return 0 if result["ok"] else 1
    if os.environ.get("BENCH_MODE") == "feature2d":
        # 2-D (rows x features) windowed-round dryrun (round 24): the
        # fused round over the (feature, row) mesh — per-feature-block
        # histograms complete by layout (ZERO feature-axis collectives
        # in the histogram phase), owned-feature election, winner's
        # go/no-go row broadcast — validated for structural tree
        # equality vs serial windowed growth + the per-rank round
        # budget, with the per-axis collective byte bills from the
        # jaxpr audit embedded in-artifact.  Writes MULTICHIP_r08-format
        # JSON.
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import __graft_entry__ as _ge

        d_r = int(os.environ.get("FEATURE2D_ROW_SHARDS", "2"))
        d_f = int(os.environ.get("FEATURE2D_FEATURE_SHARDS", "4"))
        grids = [(d_r, d_f, False), (d_f, d_r, True)]
        result = {"mode": "feature2d_windowed", "grids": {}, "ok": True}
        for rows, feats, quant in grids:
            import io
            from contextlib import redirect_stdout

            key = f"{rows}x{feats}" + ("_int8" if quant else "_float")
            buf = io.StringIO()
            try:
                with redirect_stdout(buf):
                    _ge.dryrun_feature2d_windowed(rows, feats, quant)
                result["grids"][key] = {
                    "rc": 0, "ok": True,
                    "tail": buf.getvalue()[-500:]}
            except Exception as e:  # noqa: BLE001 — artifact robustness
                result["grids"][key] = {
                    "rc": 1, "ok": False,
                    "tail": (buf.getvalue() + f"\n{type(e).__name__}: "
                             f"{e}")[-800:]}
                result["ok"] = False
        # the per-axis byte bills, proven on the traced IR: the feature
        # axis budget (go/no-go broadcast + election, no histograms)
        # rides the artifact next to the row-axis histogram merge bill
        try:
            from lightgbm_tpu.analysis.jaxpr_audit import run_jaxpr_audit

            rep = run_jaxpr_audit(
                ["windowed_round_2d_float",
                 "windowed_round_2d_quantized"], runtime=False)
            result["jaxpr_audit"] = {
                r.name: {"ok": r.ok,
                         "axis_bytes": r.detail.get("axis_bytes"),
                         "feature_bytes": r.detail.get("feature_bytes")}
                for r in rep.results}
            result["ok"] = result["ok"] and rep.ok
        except Exception as e:  # noqa: BLE001 — artifact robustness
            result["jaxpr_audit"] = {"error": f"{type(e).__name__}: {e}"}
            result["ok"] = False
        print(json.dumps(result, indent=2))
        return 0 if result["ok"] else 1
    # persistent XLA compilation cache (measured r5: cuts warmups ~2.4x on
    # the second process — kernel smoke 31->21 s, primary compile
    # 104->43 s — the warmups were the reason Epsilon kept falling off the
    # budget).  Must be set before the first jax import; bench only
    # imports jax inside workload fns, so here is early enough.
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_cache", "jaxcache"))
    n = int(os.environ.get("BENCH_ROWS", 1_000_000))
    f = 28
    iters = int(os.environ.get("BENCH_ITERS", 30))
    max_bin = int(os.environ.get("BENCH_MAX_BIN", 63))
    fast = os.environ.get("BENCH_FAST", "0") == "1"

    rng = np.random.RandomState(0)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    y = ((X @ w + 0.3 * rng.randn(n)) > 0).astype(np.float64)

    base_params = {
        "num_leaves": 31,
        "learning_rate": 0.1,
        "verbosity": -1,
        "min_data_in_leaf": 20,
    }

    # ---- 0: Pallas kernel smoke (<60 s, always first, always captured) ----
    _guarded("pallas_smoke", _pallas_smoke)

    # ---- jaxpr audit verdict (docs/ANALYSIS.md "Jaxpr audit layer"),
    # after the smoke so its budget can never displace the one workload
    # promised 'always captured': trace the flagship executables and
    # embed the contract verdict next to the telemetry snapshot, so
    # chip-session artifact rows carry proof the one-dispatch/
    # one-collective/all-donated contracts held at trace time.
    # Trace/lower ONLY: the runtime ledger check AND the execution-
    # needing contracts (the converted-predict toy booster) are skipped —
    # on chip either would pay real remote compiles out of the bench
    # budget; the verdict lists what it skipped. ----
    def _embed_audit():
        from lightgbm_tpu.analysis.jaxpr_audit import verdict

        _STATE["jaxpr_audit"] = verdict(runtime=False, exec_contracts=False)

    _guarded("jaxpr_audit", _embed_audit, budget_floor=30.0)

    # ---- 1: primary Higgs-like binary at the device-recommended width ----
    primary_name = f"binary_{n//1000}k_x{f}f_{max_bin}bins"

    def wprimary():
        ips, warm, rates = _run(dict(base_params, objective="binary",
                                     max_bin=max_bin), X, y, iters=iters,
                                repeats=3)
        vs = ips * (n / 10_500_000.0) / _BASELINE_IPS
        _record(primary_name, ips, warm, vs,
                extra={"repeats": [round(r, 2) for r in rates]})
        _STATE["metric"] = (
            f"boosting_iters_per_sec_binary_{n//1000}k_rows_x{f}f_{max_bin}bins")
        _STATE["value"] = round(ips, 3)
        _STATE["vs_baseline"] = round(vs, 3)

    _guarded(primary_name, wprimary, budget_floor=5.0)

    if not fast:
        # extra workloads scale with BENCH_ROWS so smoke runs stay cheap
        scale = n / 1_000_000.0

        # ---- 2: Epsilon-class wide 255-bin, SECOND (two rounds of
        # budget-skips left the wide regime unverified in the artifact —
        # VERDICT r4 item 2 — and the r5 warmup reality put it out of
        # reach even in third position).  One bin width only (255, the
        # reference-default config); the 63-bin variant is ledgered in
        # PERF_NOTES.  The binned dataset loads from the save_binary
        # cache when present (host binning at 400k x 2000 is ~7 min —
        # never affordable in-budget). ----
        ne = max(int(400_000 * scale), 2000)
        fe = 2000 if scale >= 0.05 else 200
        name_e = f"epsilon_{ne//1000}k_x{fe}f_255bins"

        def weps():
            import lightgbm_tpu as lgb
            from lightgbm_tpu.utils.profiling import timed_section
            cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".bench_cache", "epsilon_255.bin")
            eparams = dict(base_params, objective="binary", max_bin=255,
                           num_leaves=255)
            with timed_section("bench_dataset_bin"):
                if os.path.exists(cache) and fe == 2000:
                    ds = lgb.Dataset(cache, params={"max_bin": 255})
                    from_cache = True
                elif _remaining() > (420 if fe == 2000 else 30):
                    rng_e = np.random.RandomState(1)
                    Xe = rng_e.randn(ne, fe).astype(np.float32)
                    ye = ((Xe[:, :64] @ rng_e.randn(64) + rng_e.randn(ne))
                          > 0).astype(np.float64)
                    ds = lgb.Dataset(Xe, label=ye, params={"max_bin": 255})
                    from_cache = False
                else:
                    _STATE["workloads"][name_e] = {
                        "skipped": "no cache and insufficient budget to bin"}
                    return
            t0 = time.perf_counter()
            with timed_section("bench_warmup"):
                bst = lgb.Booster(params=eparams, train_set=ds)
                bst.update()
                _ = np.asarray(bst._gbdt._score[:8])  # true drain (tunnel)
            warme = time.perf_counter() - t0
            t0 = time.perf_counter()
            e_iters = 5
            with timed_section("bench_train_iters"):
                for _i in range(e_iters):
                    bst.update()
                _ = np.asarray(bst._gbdt._score[:8])
            dte = time.perf_counter() - t0
            ipse = e_iters / dte
            _record(name_e, ipse, warme, None,
                    extra={"sec_per_iter": round(dte / e_iters, 2),
                           "from_cache": from_cache,
                           "quantized_default": bool(
                               bst._gbdt.cfg.use_quantized_grad)})
        _guarded(name_e, weps, budget_floor=60.0)

        # ---- 3: reference-default max_bin=255 (VERDICT r2 item 1) ----
        if max_bin != 255:
            name255 = f"binary_{n//1000}k_x{f}f_255bins"

            def w255():
                ips255, warm255, _r = _run(
                    dict(base_params, objective="binary", max_bin=255),
                    X, y, iters=max(iters // 2, 5))
                _record(name255, ips255, warm255,
                        ips255 * (n / 10_500_000.0) / _BASELINE_IPS)
            _guarded(name255, w255)

        # data generation happens INSIDE each guarded fn so an exhausted
        # budget skips the (multi-GB at full scale) allocation too

        # ---- 4: MSLR-shaped LambdaRank (ranking objective path) ----
        nr = max(int(240_000 * scale) // 120 * 120, 2400)
        fr, docs = 136, 120
        name_rank = f"lambdarank_{nr//1000}k_x{fr}f_q{docs}_{max_bin}bins"

        def wrank():
            rng_r = np.random.RandomState(2)
            Xr = rng_r.randn(nr, fr).astype(np.float32)
            rel = np.clip((Xr[:, :16] @ rng_r.randn(16)) * 0.8
                          + rng_r.randn(nr), -2.5, 2.49)
            yr = np.clip(np.floor(rel) + 2, 0, 4).astype(np.float64)
            gr = np.full(nr // docs, docs)
            ipsr, warmr, _rr = _run(
                dict(base_params, objective="lambdarank", max_bin=max_bin),
                Xr, yr, group=gr, iters=max(iters // 2, 5))
            _record(name_rank, ipsr, warmr, None)
        _guarded(name_rank, wrank)

        # ---- 5: multiclass (Airline-style softmax, K trees/iter) ----
        nm, km = max(int(500_000 * scale), 5000), 5
        name_mc = f"multiclass{km}_{nm//1000}k_x{f}f_{max_bin}bins"

        def wmc():
            rng_m = np.random.RandomState(3)
            Xm = rng_m.randn(nm, f).astype(np.float32)
            ym = np.argmax(Xm[:, :km] + 0.5 * rng_m.randn(nm, km),
                           axis=1).astype(np.float64)
            ipsm, warmm, _rm = _run(
                dict(base_params, objective="multiclass", num_class=km,
                     max_bin=max_bin),
                Xm, ym, iters=max(iters // 2, 5))
            _record(name_mc, ipsm, warmm, None)
        _guarded(name_mc, wmc)

    _STATE["elapsed_s"] = round(time.monotonic() - _T0, 1)
    _emit()


if __name__ == "__main__":
    main()
