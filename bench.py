"""Benchmark: Higgs-like binary GBDT training throughput on the real chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Baseline anchor (BASELINE.md, LOW CONFIDENCE until the reference mount is
populated): reference CPU training of Higgs 10.5M x 28 runs 500 boosting
iterations in ~240 s => ~2.08 iters/sec on a dual-Xeon of the docs era.
vs_baseline = our_iters_per_sec / 2.08 on a synthetic dataset with the same
feature count (1M rows here to keep bench wall-clock sane; the hist cost is
linear in rows, so iters/sec at 10.5M rows ~ value/10.5).

Bin width: the bench trains the device-recommended `max_bin=63`
configuration — the same choice the reference's own GPU benchmarks make
against the CPU's 255 (docs/GPU-Performance.rst), and the metric name says
so.  Measured accuracy parity for this workload (docs/PERF_NOTES.md):
test AUC 0.93757 @63 bins vs 0.93735 @255 bins.  Set BENCH_MAX_BIN=255 to
measure the full-width configuration (tracked in PERF_NOTES).
"""

import json
import os
import sys
import time

import numpy as np


def main():
    n = int(os.environ.get("BENCH_ROWS", 1_000_000))
    f = 28
    iters = int(os.environ.get("BENCH_ITERS", 30))
    max_bin = int(os.environ.get("BENCH_MAX_BIN", 63))

    import jax

    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    y = ((X @ w + 0.3 * rng.randn(n)) > 0).astype(np.float64)

    params = {
        "objective": "binary",
        "num_leaves": 31,
        "max_bin": max_bin,
        "learning_rate": 0.1,
        "verbosity": -1,
        "min_data_in_leaf": 20,
    }
    train = lgb.Dataset(X, label=y)
    # warmup: construct + compile (first tree triggers all jit compiles)
    bst = lgb.Booster(params=params, train_set=train)
    bst.update()
    jax.block_until_ready(bst._gbdt._score)

    t0 = time.perf_counter()
    for _ in range(iters):
        bst.update()
    jax.block_until_ready(bst._gbdt._score)
    dt = time.perf_counter() - t0
    ips = iters / dt

    baseline_ips = 500.0 / 240.0  # reference CPU Higgs anchor (BASELINE.md)
    # scale our 1M-row rate to the baseline's 10.5M rows (linear in rows)
    ips_at_higgs_scale = ips * (n / 10_500_000.0)
    print(
        json.dumps(
            {
                "metric": f"boosting_iters_per_sec_binary_{n//1000}k_rows_x{f}f_{max_bin}bins",
                "value": round(ips, 3),
                "unit": "iters/sec",
                "vs_baseline": round(ips_at_higgs_scale / baseline_ips, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
