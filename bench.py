"""Benchmark: GBDT training throughput on the real chip, multiple workloads.

Prints ONE JSON line.  Primary fields {"metric", "value", "unit",
"vs_baseline"} track the headline Higgs-like binary workload at the
device-recommended max_bin=63 (accuracy parity measured in
docs/PERF_NOTES.md: AUC 0.93757 @63 vs 0.93735 @255); the "workloads"
object adds the reference-default max_bin=255 configuration, an
Epsilon-class wide shape, an MSLR-shaped LambdaRank run and a multiclass
run (BASELINE.json configs; VERDICT r2 item 10).

Baseline anchor (BASELINE.md, LOW CONFIDENCE until the reference mount is
populated): reference CPU training of Higgs 10.5M x 28 runs 500 boosting
iterations in ~240 s => ~2.08 iters/sec.  vs_baseline = our iters/sec
linearly scaled to 10.5M rows / 2.08.  Workloads without a published
reference number carry vs_baseline: null.

Env knobs: BENCH_ROWS, BENCH_ITERS, BENCH_MAX_BIN (primary workload),
BENCH_FAST=1 (primary workload only — skips the extras).
"""

import json
import os
import time

import numpy as np

_BASELINE_IPS = 500.0 / 240.0  # reference CPU Higgs anchor (BASELINE.md)


def _run(params, X, y, group=None, iters=30):
    """Train `iters` timed iterations; returns (iters/sec, warmup_s)."""
    import jax
    import lightgbm_tpu as lgb

    ds = lgb.Dataset(X, label=y, group=group)
    t0 = time.perf_counter()
    bst = lgb.Booster(params=params, train_set=ds)
    bst.update()
    jax.block_until_ready(bst._gbdt._score)
    warmup = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        bst.update()
    jax.block_until_ready(bst._gbdt._score)
    dt = time.perf_counter() - t0
    return iters / dt, warmup


def main():
    n = int(os.environ.get("BENCH_ROWS", 1_000_000))
    f = 28
    iters = int(os.environ.get("BENCH_ITERS", 30))
    max_bin = int(os.environ.get("BENCH_MAX_BIN", 63))
    fast = os.environ.get("BENCH_FAST", "0") == "1"

    rng = np.random.RandomState(0)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    y = ((X @ w + 0.3 * rng.randn(n)) > 0).astype(np.float64)

    base_params = {
        "num_leaves": 31,
        "learning_rate": 0.1,
        "verbosity": -1,
        "min_data_in_leaf": 20,
    }

    workloads = {}

    def record(name, ips, warmup, vs=None, extra=None):
        entry = {"iters_per_sec": round(ips, 3), "warmup_s": round(warmup, 1),
                 "vs_baseline": vs if vs is None else round(vs, 3)}
        if extra:
            entry.update(extra)
        workloads[name] = entry
        return entry

    # ---- primary: Higgs-like binary at the device-recommended bin width ----
    ips, warm = _run(dict(base_params, objective="binary", max_bin=max_bin),
                     X, y, iters=iters)
    vs_primary = ips * (n / 10_500_000.0) / _BASELINE_IPS
    record(f"binary_{n//1000}k_x{f}f_{max_bin}bins", ips, warm, vs_primary)

    def guarded(name, fn):
        """One workload; a failure (e.g. transient remote-compile error)
        records an error entry instead of killing the whole artifact."""
        try:
            fn()
        except Exception as e:  # noqa: BLE001 - artifact robustness
            workloads[name] = {"error": f"{type(e).__name__}: {e}"[:300]}

    if not fast:
        # ---- reference-default max_bin=255 (VERDICT r2 item 1) ----
        if max_bin != 255:
            def w255():
                ips255, warm255 = _run(
                    dict(base_params, objective="binary", max_bin=255),
                    X, y, iters=max(iters // 2, 5))
                record(f"binary_{n//1000}k_x{f}f_255bins", ips255, warm255,
                       ips255 * (n / 10_500_000.0) / _BASELINE_IPS)
            guarded(f"binary_{n//1000}k_x{f}f_255bins", w255)

        # extra workloads scale with BENCH_ROWS so smoke runs stay cheap
        scale = n / 1_000_000.0
        # ---- Epsilon-class wide shape (400k x 2000; VERDICT r2 item 2) ----
        ne = max(int(400_000 * scale), 2000)
        fe = 2000 if scale >= 0.05 else 200
        rng_e = np.random.RandomState(1)
        Xe = rng_e.randn(ne, fe).astype(np.float32)
        ye = ((Xe[:, :64] @ rng_e.randn(64) + rng_e.randn(ne)) > 0).astype(np.float64)
        for eb in (63, 255):
            def weps(eb=eb):
                ipse, warme = _run(
                    dict(base_params, objective="binary", max_bin=eb,
                         num_leaves=255),
                    Xe, ye, iters=5)
                record(f"epsilon_{ne//1000}k_x{fe}f_{eb}bins", ipse, warme,
                       None,
                       extra={"sec_per_iter": round(1.0 / max(ipse, 1e-9), 2)})
            guarded(f"epsilon_{ne//1000}k_x{fe}f_{eb}bins", weps)
        del Xe, ye

        # ---- MSLR-shaped LambdaRank (ranking objective path) ----
        nr = max(int(240_000 * scale) // 120 * 120, 2400)
        fr, docs = 136, 120
        rng_r = np.random.RandomState(2)
        Xr = rng_r.randn(nr, fr).astype(np.float32)
        rel = np.clip((Xr[:, :16] @ rng_r.randn(16)) * 0.8 + rng_r.randn(nr),
                      -2.5, 2.49)
        yr = np.clip(np.floor(rel) + 2, 0, 4).astype(np.float64)
        gr = np.full(nr // docs, docs)
        def wrank():
            ipsr, warmr = _run(
                dict(base_params, objective="lambdarank", max_bin=max_bin),
                Xr, yr, group=gr, iters=max(iters // 2, 5))
            record(f"lambdarank_{nr//1000}k_x{fr}f_q{docs}_{max_bin}bins",
                   ipsr, warmr, None)
        guarded(f"lambdarank_{nr//1000}k_x{fr}f_q{docs}_{max_bin}bins", wrank)

        # ---- multiclass (Airline-style softmax, K trees/iter) ----
        nm, km = max(int(500_000 * scale), 5000), 5
        rng_m = np.random.RandomState(3)
        Xm = rng_m.randn(nm, f).astype(np.float32)
        ym = np.argmax(Xm[:, :km] + 0.5 * rng_m.randn(nm, km), axis=1).astype(np.float64)
        def wmc():
            ipsm, warmm = _run(
                dict(base_params, objective="multiclass", num_class=km,
                     max_bin=max_bin),
                Xm, ym, iters=max(iters // 2, 5))
            record(f"multiclass{km}_{nm//1000}k_x{f}f_{max_bin}bins",
                   ipsm, warmm, None)
        guarded(f"multiclass{km}_{nm//1000}k_x{f}f_{max_bin}bins", wmc)

    primary = workloads[f"binary_{n//1000}k_x{f}f_{max_bin}bins"]
    print(json.dumps({
        "metric": f"boosting_iters_per_sec_binary_{n//1000}k_rows_x{f}f_{max_bin}bins",
        "value": primary["iters_per_sec"],
        "unit": "iters/sec",
        "vs_baseline": primary["vs_baseline"],
        "workloads": workloads,
    }))


if __name__ == "__main__":
    main()
