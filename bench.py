"""Benchmark: GBDT training throughput on the real chip, multiple workloads.

Artifact contract (un-losable by design): a parseable JSON line with
{"metric", "value", "unit", "vs_baseline", "workloads"} is printed and
flushed after EVERY completed workload — the last line on stdout is always
the most complete snapshot, so a driver timeout mid-run still captures
everything measured so far.  That incremental emission is the primary
guarantee; a SIGTERM/SIGALRM handler additionally emits a final snapshot
when Python-level code is running (signals are deferred while blocked
inside a C call, e.g. a hung remote compile — in that case the
already-printed lines are what survives), and a global wall-clock budget
(BENCH_BUDGET_S, default 450 s) skips not-yet-started workloads as
{"skipped": "budget"} rather than losing the artifact.

Ordering is cheap-first: (0) a <60 s Pallas-kernel smoke (direct
histogram kernel execution, checksummed against numpy — closes the
eval_shape-only CI hole for the kernel path), (1) the headline Higgs-like
binary workload at the device-recommended max_bin=63 (accuracy parity
measured in docs/PERF_NOTES.md: AUC 0.93757 @63 vs 0.93735 @255), then
the reference-default max_bin=255 configuration, multiclass, LambdaRank,
and the Epsilon-class wide shapes (most expensive last).

Baseline anchor (BASELINE.md, LOW CONFIDENCE until the reference mount is
populated): reference CPU training of Higgs 10.5M x 28 runs 500 boosting
iterations in ~240 s => ~2.08 iters/sec.  vs_baseline = our iters/sec
linearly scaled to 10.5M rows / 2.08.  Workloads without a published
reference number carry vs_baseline: null.

Env knobs: BENCH_ROWS, BENCH_ITERS, BENCH_MAX_BIN (primary workload),
BENCH_FAST=1 (smoke + primary only), BENCH_BUDGET_S (global budget).
"""

import json
import os
import signal
import sys
import time

import numpy as np

_BASELINE_IPS = 500.0 / 240.0  # reference CPU Higgs anchor (BASELINE.md)

_T0 = time.monotonic()
_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", 450))

# mutable artifact state: emit() prints a full snapshot of this at any time
_STATE = {
    "metric": "boosting_iters_per_sec",
    "value": None,
    "unit": "iters/sec",
    "vs_baseline": None,
    "workloads": {},
}


def _emit():
    line = json.dumps(_STATE) + "\n"
    sys.stdout.write(line)
    sys.stdout.flush()


def _emit_raw():
    """Signal-handler-safe emission: bypass buffered stdout.  The leading
    newline terminates any partially flushed line the signal interrupted,
    so this snapshot always starts (and ends) a clean line."""
    try:
        os.write(1, ("\n" + json.dumps(_STATE) + "\n").encode())
    except Exception:
        pass


def _on_term(signum, frame):  # noqa: ARG001 - signal signature
    _STATE["interrupted"] = {
        "signal": signum, "elapsed_s": round(time.monotonic() - _T0, 1)}
    _emit_raw()
    os._exit(128 + signum)


signal.signal(signal.SIGTERM, _on_term)
signal.signal(signal.SIGINT, _on_term)


class _BudgetExceeded(Exception):
    pass


def _on_alarm(signum, frame):  # noqa: ARG001
    raise _BudgetExceeded()


signal.signal(signal.SIGALRM, _on_alarm)


def _remaining():
    return _BUDGET_S - (time.monotonic() - _T0)


def _run(params, X, y, group=None, iters=30):
    """Train `iters` timed iterations; returns (iters/sec, warmup_s)."""
    import jax
    import lightgbm_tpu as lgb

    ds = lgb.Dataset(X, label=y, group=group)
    t0 = time.perf_counter()
    bst = lgb.Booster(params=params, train_set=ds)
    bst.update()
    jax.block_until_ready(bst._gbdt._score)
    warmup = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        bst.update()
    jax.block_until_ready(bst._gbdt._score)
    dt = time.perf_counter() - t0
    return iters / dt, warmup


def _record(name, ips, warmup, vs=None, extra=None):
    entry = {"iters_per_sec": round(ips, 3), "warmup_s": round(warmup, 1),
             "vs_baseline": vs if vs is None else round(vs, 3)}
    if extra:
        entry.update(extra)
    _STATE["workloads"][name] = entry
    return entry


def _guarded(name, fn, budget_floor=15.0):
    """Run one workload inside the global budget.

    Skips (recording {"skipped": "budget"}) if less than `budget_floor`
    seconds remain; arms SIGALRM for the remaining budget as a best-effort
    over-run stop (it fires between Python bytecodes — a call truly stuck
    inside C is only bounded by the driver's own timeout, against which
    the incremental per-workload emission preserves the artifact); any
    other failure (e.g. transient remote-compile error) records an error
    entry instead of killing the whole run.  Emits a fresh artifact
    snapshot after every outcome.
    """
    rem = _remaining()
    if rem < budget_floor:
        _STATE["workloads"][name] = {"skipped": "budget"}
        _emit()
        return
    try:
        try:
            signal.alarm(max(int(rem), 1))
            fn()
        finally:
            # a late alarm can still fire here before alarm(0) runs — the
            # outer except absorbs it (and the unconditional alarm(0) below
            # covers the skipped disarm)
            signal.alarm(0)
    except _BudgetExceeded:
        # keep an entry fn() already recorded (the alarm may land between
        # the measurement and the return) — only mark error if none exists
        _STATE["workloads"].setdefault(
            name, {"error": "budget exceeded mid-workload"})
    except Exception as e:  # noqa: BLE001 - artifact robustness
        _STATE["workloads"][name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    signal.alarm(0)
    _emit()


def _pallas_smoke():
    """Execute the real Pallas histogram kernel on-chip at a tiny shape and
    checksum it against numpy (VERDICT r3 weak #6: CI only eval_shapes the
    Pallas path; this guarantees one real kernel execution per round)."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops.hist_pallas import histogram_pallas_multi

    n, f, b, tile = 16384, 28, 256, 4
    rng = np.random.RandomState(7)
    bins = rng.randint(0, b, size=(n, f)).astype(np.int16)
    g = rng.randn(n).astype(np.float32)
    h = np.abs(rng.randn(n)).astype(np.float32) + 0.1
    leaf = rng.randint(0, tile, size=n).astype(np.int32)
    mask = np.ones(n, bool)

    t0 = time.perf_counter()
    out = histogram_pallas_multi(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(mask), jnp.asarray(leaf), 0, tile, b)
    out = np.asarray(jax.block_until_ready(out))
    elapsed = time.perf_counter() - t0

    # numpy oracle for slot 0 / feature 0 (out is channel-first (L, 3, F, B))
    ref = np.zeros((b, 3))
    sel = leaf == 0
    np.add.at(ref, bins[sel, 0], np.stack([g[sel], h[sel],
                                           np.ones(sel.sum())], axis=1))
    ok = bool(np.allclose(out[0, 0, 0, :], ref[:, 0], atol=1e-2)
              and np.allclose(out[0, 2, 0, :], ref[:, 2], atol=0.5))
    _STATE["workloads"]["pallas_smoke"] = {
        "ok": ok, "kernel_s": round(elapsed, 1),
        "platform": jax.devices()[0].platform}
    if not ok:
        # surface the miscomputation as a hard error entry too (_guarded
        # rewrites this workload's entry), not just a nested flag
        raise AssertionError(
            f"pallas kernel checksum FAILED on {jax.devices()[0].platform}")


def main():
    n = int(os.environ.get("BENCH_ROWS", 1_000_000))
    f = 28
    iters = int(os.environ.get("BENCH_ITERS", 30))
    max_bin = int(os.environ.get("BENCH_MAX_BIN", 63))
    fast = os.environ.get("BENCH_FAST", "0") == "1"

    rng = np.random.RandomState(0)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    y = ((X @ w + 0.3 * rng.randn(n)) > 0).astype(np.float64)

    base_params = {
        "num_leaves": 31,
        "learning_rate": 0.1,
        "verbosity": -1,
        "min_data_in_leaf": 20,
    }

    # ---- 0: Pallas kernel smoke (<60 s, always first, always captured) ----
    _guarded("pallas_smoke", _pallas_smoke)

    # ---- 1: primary Higgs-like binary at the device-recommended width ----
    primary_name = f"binary_{n//1000}k_x{f}f_{max_bin}bins"

    def wprimary():
        ips, warm = _run(dict(base_params, objective="binary",
                              max_bin=max_bin), X, y, iters=iters)
        vs = ips * (n / 10_500_000.0) / _BASELINE_IPS
        _record(primary_name, ips, warm, vs)
        _STATE["metric"] = (
            f"boosting_iters_per_sec_binary_{n//1000}k_rows_x{f}f_{max_bin}bins")
        _STATE["value"] = round(ips, 3)
        _STATE["vs_baseline"] = round(vs, 3)

    _guarded(primary_name, wprimary, budget_floor=5.0)

    if not fast:
        # ---- 2: reference-default max_bin=255 (VERDICT r2 item 1) ----
        if max_bin != 255:
            name255 = f"binary_{n//1000}k_x{f}f_255bins"

            def w255():
                ips255, warm255 = _run(
                    dict(base_params, objective="binary", max_bin=255),
                    X, y, iters=max(iters // 2, 5))
                _record(name255, ips255, warm255,
                        ips255 * (n / 10_500_000.0) / _BASELINE_IPS)
            _guarded(name255, w255)

        # extra workloads scale with BENCH_ROWS so smoke runs stay cheap
        scale = n / 1_000_000.0

        # data generation happens INSIDE each guarded fn so an exhausted
        # budget skips the (multi-GB at full scale) allocation too

        # ---- 3: multiclass (Airline-style softmax, K trees/iter) ----
        nm, km = max(int(500_000 * scale), 5000), 5
        name_mc = f"multiclass{km}_{nm//1000}k_x{f}f_{max_bin}bins"

        def wmc():
            rng_m = np.random.RandomState(3)
            Xm = rng_m.randn(nm, f).astype(np.float32)
            ym = np.argmax(Xm[:, :km] + 0.5 * rng_m.randn(nm, km),
                           axis=1).astype(np.float64)
            ipsm, warmm = _run(
                dict(base_params, objective="multiclass", num_class=km,
                     max_bin=max_bin),
                Xm, ym, iters=max(iters // 2, 5))
            _record(name_mc, ipsm, warmm, None)
        _guarded(name_mc, wmc)

        # ---- 4: MSLR-shaped LambdaRank (ranking objective path) ----
        nr = max(int(240_000 * scale) // 120 * 120, 2400)
        fr, docs = 136, 120
        name_rank = f"lambdarank_{nr//1000}k_x{fr}f_q{docs}_{max_bin}bins"

        def wrank():
            rng_r = np.random.RandomState(2)
            Xr = rng_r.randn(nr, fr).astype(np.float32)
            rel = np.clip((Xr[:, :16] @ rng_r.randn(16)) * 0.8
                          + rng_r.randn(nr), -2.5, 2.49)
            yr = np.clip(np.floor(rel) + 2, 0, 4).astype(np.float64)
            gr = np.full(nr // docs, docs)
            ipsr, warmr = _run(
                dict(base_params, objective="lambdarank", max_bin=max_bin),
                Xr, yr, group=gr, iters=max(iters // 2, 5))
            _record(name_rank, ipsr, warmr, None)
        _guarded(name_rank, wrank)

        # ---- 5: Epsilon-class wide shape (400k x 2000, most expensive) ----
        ne = max(int(400_000 * scale), 2000)
        fe = 2000 if scale >= 0.05 else 200
        eps_data = []  # generated once by the first un-skipped workload

        def eps_xy():
            if not eps_data:
                rng_e = np.random.RandomState(1)
                Xe = rng_e.randn(ne, fe).astype(np.float32)
                ye = ((Xe[:, :64] @ rng_e.randn(64) + rng_e.randn(ne))
                      > 0).astype(np.float64)
                eps_data.extend([Xe, ye])
            return eps_data[0], eps_data[1]

        for eb in (63, 255):
            name_e = f"epsilon_{ne//1000}k_x{fe}f_{eb}bins"

            def weps(eb=eb, name_e=name_e):
                Xe, ye = eps_xy()
                ipse, warme = _run(
                    dict(base_params, objective="binary", max_bin=eb,
                         num_leaves=255),
                    Xe, ye, iters=5)
                _record(name_e, ipse, warme, None,
                        extra={"sec_per_iter": round(1.0 / max(ipse, 1e-9), 2)})
            _guarded(name_e, weps, budget_floor=45.0)
        eps_data.clear()

    _STATE["elapsed_s"] = round(time.monotonic() - _T0, 1)
    _emit()


if __name__ == "__main__":
    main()
