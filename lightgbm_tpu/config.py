"""Parameter system: names, defaults, aliases.

TPU-native re-implementation of the reference's config layer
(reference: include/LightGBM/config.h, src/io/config.cpp,
src/io/config_auto.cpp -> Config::Set / parameter2aliases).  The reference
generates its alias tables from docs/Parameters.rst; here a single Python
table is the source of truth.

Only a (large) subset of the ~180 params is meaningful yet; unknown params are
accepted and kept (LightGBM behavior: warn-and-ignore for unused params).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field, fields
from typing import Any, Dict, List, Optional, Union

# ---------------------------------------------------------------------------
# Alias table (reference: Config::parameter2aliases in src/io/config_auto.cpp)
# maps alias -> canonical name.
# ---------------------------------------------------------------------------
_ALIASES: Dict[str, str] = {
    # core
    "config_file": "config",
    "task_type": "task",
    "objective_type": "objective",
    "app": "objective",
    "application": "objective",
    "loss": "objective",
    "boosting_type": "boosting",
    "boost": "boosting",
    "train": "data",
    "train_data": "data",
    "train_data_file": "data",
    "data_filename": "data",
    "test": "valid",
    "valid_data": "valid",
    "valid_data_file": "valid",
    "test_data": "valid",
    "test_data_file": "valid",
    "valid_filenames": "valid",
    "num_iteration": "num_iterations",
    "n_iter": "num_iterations",
    "num_tree": "num_iterations",
    "num_trees": "num_iterations",
    "num_round": "num_iterations",
    "num_rounds": "num_iterations",
    "nrounds": "num_iterations",
    "num_boost_round": "num_iterations",
    "n_estimators": "num_iterations",
    "max_iter": "num_iterations",
    "shrinkage_rate": "learning_rate",
    "eta": "learning_rate",
    "num_leaf": "num_leaves",
    "max_leaves": "num_leaves",
    "max_leaf": "num_leaves",
    "max_leaf_nodes": "num_leaves",
    "tree": "tree_learner",
    "tree_type": "tree_learner",
    "tree_learner_type": "tree_learner",
    "num_thread": "num_threads",
    "nthread": "num_threads",
    "nthreads": "num_threads",
    "n_jobs": "num_threads",
    "device": "device_type",
    "random_seed": "seed",
    "random_state": "seed",
    # learning control
    "min_data_per_leaf": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_samples_leaf": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "sub_row": "bagging_fraction",
    "subsample": "bagging_fraction",
    "bagging": "bagging_fraction",
    "pos_sub_row": "pos_bagging_fraction",
    "pos_subsample": "pos_bagging_fraction",
    "pos_bagging": "pos_bagging_fraction",
    "neg_sub_row": "neg_bagging_fraction",
    "neg_subsample": "neg_bagging_fraction",
    "neg_bagging": "neg_bagging_fraction",
    "subsample_freq": "bagging_freq",
    "bagging_fraction_seed": "bagging_seed",
    "sub_feature": "feature_fraction",
    "colsample_bytree": "feature_fraction",
    "sub_feature_bynode": "feature_fraction_bynode",
    "colsample_bynode": "feature_fraction_bynode",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "n_iter_no_change": "early_stopping_round",
    "max_tree_output": "max_delta_step",
    "max_leaf_output": "max_delta_step",
    "reg_alpha": "lambda_l1",
    "l1_regularization": "lambda_l1",
    "reg_lambda": "lambda_l2",
    "lambda": "lambda_l2",
    "l2_regularization": "lambda_l2",
    "min_split_gain": "min_gain_to_split",
    "rate_drop": "drop_rate",
    "topk": "top_k",
    "mc": "monotone_constraints",
    "feature_contrib": "feature_contri",
    "fc": "feature_contri",
    "fp": "feature_contri",
    "feature_penalty": "feature_contri",
    "fs": "forcedsplits_filename",
    "forced_splits_filename": "forcedsplits_filename",
    "forced_splits_file": "forcedsplits_filename",
    "forced_splits": "forcedsplits_filename",
    "monotone_constraint": "monotone_constraints",
    "monotonic_cst": "monotone_constraints",
    "monotone_constraining_method": "monotone_constraints_method",
    "mc_method": "monotone_constraints_method",
    "monotone_splits_penalty": "monotone_penalty",
    "ms_penalty": "monotone_penalty",
    "mc_penalty": "monotone_penalty",
    "interaction_constraint": "interaction_constraints",
    "verbose": "verbosity",
    "model_output": "output_model",
    "model_out": "output_model",
    "save_period": "snapshot_freq",
    "model_input": "input_model",
    "model_in": "input_model",
    "predict_result": "output_result",
    "prediction_result": "output_result",
    "predict_name": "output_result",
    "prediction_name": "output_result",
    "pred_name": "output_result",
    "name_pred": "output_result",
    "is_pre_partition": "pre_partition",
    "is_enable_bundle": "enable_bundle",
    "bundle": "enable_bundle",
    "is_sparse": "is_enable_sparse",
    "enable_sparse": "is_enable_sparse",
    "sparse": "is_enable_sparse",
    "two_round_loading": "two_round",
    "use_two_round_loading": "two_round",
    "is_save_binary": "save_binary",
    "is_save_binary_file": "save_binary",
    "has_header": "header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column",
    "group_id": "group_column",
    "query_column": "group_column",
    "query": "group_column",
    "query_id": "group_column",
    "ignore_feature": "ignore_column",
    "blacklist": "ignore_column",
    "cat_feature": "categorical_feature",
    "categorical_column": "categorical_feature",
    "cat_column": "categorical_feature",
    "is_predict_raw_score": "predict_raw_score",
    "predict_rawscore": "predict_raw_score",
    "raw_score": "predict_raw_score",
    "is_predict_leaf_index": "predict_leaf_index",
    "leaf_index": "predict_leaf_index",
    "is_predict_contrib": "predict_contrib",
    "contrib": "predict_contrib",
    "convert_model_file": "convert_model",
    "num_classes": "num_class",
    "unbalance": "is_unbalance",
    "unbalanced_sets": "is_unbalance",
    "metrics": "metric",
    "metric_types": "metric",
    "output_freq": "metric_freq",
    "training_metric": "is_provide_training_metric",
    "is_training_metric": "is_provide_training_metric",
    "train_metric": "is_provide_training_metric",
    "ndcg_eval_at": "eval_at",
    "ndcg_at": "eval_at",
    "map_eval_at": "eval_at",
    "map_at": "eval_at",
    "num_machine": "num_machines",
    "local_port": "local_listen_port",
    "port": "local_listen_port",
    "machine_list_file": "machine_list_filename",
    "machine_list": "machine_list_filename",
    "mlist": "machine_list_filename",
    "workers": "machines",
    "nodes": "machines",
    "subsample_for_bin": "bin_construct_sample_cnt",
    "hist_pool_size": "histogram_pool_size",
    "linear_trees": "linear_tree",
    "max_bins": "max_bin",
    "extra_tree": "extra_trees",
    "data_seed": "data_random_seed",
}

_OBJECTIVE_ALIASES: Dict[str, str] = {
    "regression_l2": "regression",
    "l2": "regression",
    "mean_squared_error": "regression",
    "mse": "regression",
    "l2_root": "regression",
    "root_mean_squared_error": "regression",
    "rmse": "regression",
    "regression_l1": "regression_l1",
    "l1": "regression_l1",
    "mean_absolute_error": "regression_l1",
    "mae": "regression_l1",
    "mean_absolute_percentage_error": "mape",
    "multiclassova": "multiclassova",
    "multiclass_ova": "multiclassova",
    "ova": "multiclassova",
    "ovr": "multiclassova",
    "xentropy": "cross_entropy",
    "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg",
    "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "binary_logloss": "binary",
}


def canonical_objective(name: str) -> str:
    return _OBJECTIVE_ALIASES.get(name, name)


@dataclass
class Config:
    """Typed parameter bag (reference: include/LightGBM/config.h).

    Defaults match the reference's documented defaults.
    """

    # --- core ---
    config: str = ""  # path of a config file (CLI `config=`; cli.py reads it)
    task: str = "train"
    objective: str = "regression"
    boosting: str = "gbdt"
    data_sample_strategy: str = "bagging"
    data: str = ""
    valid: List[str] = field(default_factory=list)
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    tree_learner: str = "serial"
    num_threads: int = 0
    device_type: str = "tpu"
    seed: int = 0
    deterministic: bool = False
    # TPU-specific growth scheduling (ops/treegrow_fast.py): "auto" uses the
    # round-batched grower on TPU backends and the strict best-first grower
    # elsewhere; "strict" / "rounds" force one.  Split formulas are shared
    # (ops/split.py), but the rounds grower differs from the reference in
    # leaf expansion ORDER and in histogram payload precision (see
    # hist_precision), so trees can differ from strict/CPU ones — the same
    # class of deviation the reference documents for its CUDA-vs-CPU
    # learners.
    tree_growth_mode: str = "auto"
    # histogram payload precision on the TPU MXU path: "f32" = bf16x2 split
    # payloads (~17-bit mantissa products, f32 accumulation — between the
    # reference's float and double hist modes); "bf16" = single bf16
    # payloads (~8-bit mantissa, cheapest)
    hist_precision: str = "f32"
    # fuse gradients + tree growth + score update into one jit dispatch
    # (models/gbdt.py _fused_eligible).  Disable for very wide/deep shapes
    # where the combined trace compiles slowly (e.g. Epsilon-scale
    # num_leaves=255 x 2000 features)
    fused_training: bool = True

    # --- learning control ---
    force_col_wise: bool = False
    force_row_wise: bool = False
    histogram_pool_size: float = -1.0
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    bagging_fraction: float = 1.0
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    bagging_by_query: bool = False
    feature_fraction: float = 1.0
    feature_fraction_bynode: float = 1.0
    feature_fraction_seed: int = 2
    extra_trees: bool = False
    extra_seed: int = 6
    early_stopping_round: int = 0
    early_stopping_min_delta: float = 0.0
    first_metric_only: bool = False
    max_delta_step: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    linear_lambda: float = 0.0
    min_gain_to_split: float = 0.0
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2
    other_rate: float = 0.1
    min_data_per_group: int = 100
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    top_k: int = 20
    monotone_constraints: List[int] = field(default_factory=list)
    monotone_constraints_method: str = "basic"
    monotone_penalty: float = 0.0
    feature_contri: List[float] = field(default_factory=list)
    forcedsplits_filename: str = ""
    refit_decay_rate: float = 0.9
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    cegb_penalty_feature_lazy: List[float] = field(default_factory=list)
    cegb_penalty_feature_coupled: List[float] = field(default_factory=list)
    path_smooth: float = 0.0
    interaction_constraints: Union[str, List[List[int]]] = ""
    verbosity: int = 1
    use_quantized_grad: bool = False
    num_grad_quant_bins: int = 4
    quant_train_renew_leaf: bool = False
    stochastic_rounding: bool = True

    # --- dataset ---
    linear_tree: bool = False
    max_bin: int = 255
    max_bin_by_feature: List[int] = field(default_factory=list)
    min_data_in_bin: int = 3
    bin_construct_sample_cnt: int = 200000
    data_random_seed: int = 1
    is_enable_sparse: bool = True
    enable_bundle: bool = True
    use_missing: bool = True
    zero_as_missing: bool = False
    feature_pre_filter: bool = True
    pre_partition: bool = False
    two_round: bool = False
    header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_feature: Union[str, List[int]] = ""
    forcedbins_filename: str = ""
    save_binary: bool = False
    precise_float_parser: bool = False
    parser_config_file: str = ""

    # --- predict ---
    start_iteration_predict: int = 0
    num_iteration_predict: int = -1
    predict_raw_score: bool = False
    predict_leaf_index: bool = False
    predict_contrib: bool = False
    predict_disable_shape_check: bool = False
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0
    output_result: str = "LightGBM_predict_result.txt"

    # --- convert ---
    convert_model_language: str = ""
    convert_model: str = "gbdt_prediction.cpp"

    # --- objective params ---
    objective_seed: int = 5
    num_class: int = 1
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0
    sigmoid: float = 1.0
    boost_from_average: bool = True
    reg_sqrt: bool = False
    alpha: float = 0.9
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    lambdarank_truncation_level: int = 30
    lambdarank_norm: bool = True
    label_gain: List[float] = field(default_factory=list)
    lambdarank_position_bias_regularization: float = 0.0

    # --- metric ---
    metric: List[str] = field(default_factory=list)
    metric_freq: int = 1
    is_provide_training_metric: bool = False
    eval_at: List[int] = field(default_factory=lambda: [1, 2, 3, 4, 5])
    multi_error_top_k: int = 1
    auc_mu_weights: List[float] = field(default_factory=list)

    # --- network ---
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_filename: str = ""
    machines: str = ""
    # num_slices (ours; docs/DISTRIBUTED.md "Hierarchical merge"): slice
    # count of the nested (dcn, ici) mesh for multi-slice scale-out.
    # With num_slices > 1 and tree_learner=data|voting, the fused
    # windowed round runs the two-level merge: full psum/psum_scatter
    # histogram collectives stay INSIDE each slice's ici axis, and only
    # top_k_features features' histograms + gain scalars per split
    # candidate cross the dcn axis (the PV-Tree/voting-parallel route).
    # Devices must divide evenly into slices.  1 (default) = the
    # single-level sharded round.
    num_slices: int = 1
    # top_k_features (ours; docs/DISTRIBUTED.md "Hierarchical merge"):
    # per-slice feature election width of the hierarchical merge — how
    # many features' histograms each slice may ship over DCN per split
    # candidate.  k >= num_features makes the election exhaustive
    # (trees structurally exact vs the single-mesh sharded round, at
    # full-merge byte cost over DCN); smaller k is the PV-Tree
    # approximation with a statically pinned DCN byte budget
    # (jaxpr-audit dcn_max_bytes, jaxlint R17).  Distinct from top_k,
    # which parameterizes the strict voting-parallel grower.
    top_k_features: int = 32
    # num_feature_shards (ours; docs/DISTRIBUTED.md "2-D sharding"):
    # feature-axis size d_f of the 2-D (feature, row) mesh for
    # tree_learner=feature2d — each device owns an (F/d_f, N/d_r) tile
    # of the bin matrix, per-leaf histograms are complete for the owned
    # feature block with ZERO feature-axis collectives, and the split
    # election runs the owned-feature winner machinery over the feature
    # axis.  F pads to a multiple of d_f with dead features (never
    # electable), rows pad to a multiple of d_r = devices/d_f.  A d_f
    # that does not divide the device count warns and falls back to the
    # single-level mesh instead of crashing.  1 (default) = rows-only
    # sharding.
    num_feature_shards: int = 1

    # --- GPU-compat (accepted, translated to mesh semantics) ---
    gpu_platform_id: int = -1
    gpu_device_id: int = -1
    gpu_use_dp: bool = False
    num_gpu: int = 1

    # --- io ---
    input_model: str = ""
    output_model: str = "LightGBM_model.txt"
    saved_feature_importance_type: int = 0
    snapshot_freq: int = -1
    # resume (ours; docs/ROBUSTNESS.md): "auto" resumes from the newest
    # VALID snapshot in output_model's family without naming a file; a
    # path to a fleet manifest (lgbmtpu-fleet-ckpt-v1, written by the
    # launcher's coordinated checkpoints) resumes from that FLEET-VALID
    # round — torn or unconfirmed manifests are refused.  Either way only
    # the remaining rounds toward num_iterations are trained.
    resume: str = ""
    # snapshot_keep (ours; docs/ROBUSTNESS.md "Elastic fleet recovery"):
    # retention bound for the *.snapshot_iter_<k> family (and the
    # launcher's fleet checkpoint rounds).  After each successful snapshot
    # write the oldest snapshots beyond the newest snapshot_keep are
    # pruned — but NEVER the newest one that verifies, whatever its age.
    # 0 (default) = keep all, today's behavior.
    snapshot_keep: int = 0
    # heartbeat_timeout_s (ours; docs/ROBUSTNESS.md): hang-aware fleet
    # watchdog.  Workers heartbeat by bumping the heartbeat_ts gauge at
    # every boosting round (flushed by the periodic per-rank metrics
    # snapshot — zero extra device dispatches, zero new threads); the
    # launcher declares a rank HUNG when its heartbeat goes stale past
    # this many seconds, kills its process group, and routes into the
    # max_restarts relaunch path exactly as a death does.  Size it above
    # the WORST-case round — including mid-run XLA recompiles (bucket-cap
    # transitions), not just the steady state: the host is blocked during
    # a compile, so a compile longer than the timeout reads as a hang
    # (only the very first observation is automatically excused).
    # 0 (default) = disabled (exit-code watchdog + launch timeout only).
    # LGBMTPU_HEARTBEAT_TIMEOUT_S is the env spelling.
    heartbeat_timeout_s: float = 0.0
    # slow_rank_factor (ours; docs/OBSERVABILITY.md "Fleet metrics"):
    # straggler DETECTION threshold for the launcher's heartbeat watchdog.
    # A rank whose heartbeat AGE (seconds since its value last changed)
    # exceeds slow_rank_factor x the fleet median age — and a 1 s absolute
    # floor, so an idle-but-healthy fleet's jitter can't trip it — emits a
    # fleet_slow_rank event and bumps fleet_slow_ranks_total, once per
    # slow episode.  Detection only: nothing is killed (full stalls are
    # heartbeat_timeout_s's job); the signal is for dashboards watching
    # the live launcher /metrics endpoint, where per-rank heartbeat age is
    # a labeled gauge.  0 = off.  LGBMTPU_SLOW_RANK_FACTOR is the env
    # spelling.
    slow_rank_factor: float = 3.0

    # --- out-of-core data path (ours; docs/PERF_NOTES.md round 12) ---
    # out_of_core: stream the binned matrix in row chunks through pinned,
    # reused host buffers instead of materializing it whole.  From a
    # save_binary cache the host never holds the full matrix; on device,
    # residency is governed by max_rows_in_hbm (below).  Datasets whose
    # rows exceed the device budget train via chunked histogram
    # accumulation (ops/treegrow_ooc.py) — bins are streamed per pass and
    # the device keeps only O(N) vectors + O(L*F*B) histograms.
    out_of_core: bool = False
    # max_rows_in_hbm: device-residency budget for the binned matrix, in
    # rows.  0 (default) = unbounded: the matrix is assembled device-
    # resident from the streamed chunks and training runs the standard
    # growers unchanged.  N > max_rows_in_hbm selects the spill regime
    # (chunked-histogram training).  Only meaningful with out_of_core.
    max_rows_in_hbm: int = 0
    # out_of_core_chunk_rows: rows per streamed chunk (the reused host
    # buffer's size and the device chunk shape).  0 = auto (65536).
    # Chunking never changes results: the ingest assembles the identical
    # device matrix, and the spill grower's histogram accumulation is an
    # order-preserving fold (tests/test_out_of_core.py pins bitwise
    # equality across chunk sizes).
    out_of_core_chunk_rows: int = 0

    # --- observability (ours; docs/OBSERVABILITY.md) ---
    # telemetry: the process-wide metrics/event registry (lightgbm_tpu/obs)
    # is DEFAULT-ON — it adds zero device dispatches and zero blocking
    # syncs (every device-derived metric rides an existing sync point);
    # telemetry=false flips the registry off for the process.
    telemetry: bool = True
    # metrics_file: engine.train writes the end-of-run metrics snapshot
    # (JSON, schema lgbmtpu-metrics-v1) here atomically; render it with
    # `python -m lightgbm_tpu.obs <file>`.
    metrics_file: str = ""
    # metrics_port: opt-in live HTTP endpoint (lightgbm_tpu/obs/server.py:
    # /metrics /healthz /snapshot /events) started on engine.train entry.
    # -1 = off (default), 0 = ephemeral port, >0 = that port (falling back
    # to ephemeral if busy).  LGBMTPU_METRICS_PORT is the env spelling;
    # binds 127.0.0.1 unless LGBMTPU_METRICS_HOST overrides.
    metrics_port: int = -1
    # trace_file: engine.train writes the span ring as Chrome-trace/
    # Perfetto JSON here at end of run (lightgbm_tpu/obs/trace.py; also
    # `python -m lightgbm_tpu.obs trace`).  LGBMTPU_TRACE_FILE is the env
    # spelling (the launcher sets it per worker and `python -m
    # lightgbm_tpu.obs trace --merge` folds the per-rank files).
    trace_file: str = ""
    # request_tracing: request-scoped distributed tracing (docs/
    # OBSERVABILITY.md "Request tracing") — DEFAULT-ON like telemetry=,
    # and with the same budget contract: a TraceContext minted per
    # request at admission (honoring inbound W3C traceparent on
    # /predict), threaded explicitly through coalescing/dispatch/fleet
    # retry/hedge legs, zero added device dispatches or syncs.  false
    # stops minting sampled contexts (responses still carry a trace id
    # for correlation; no spans are recorded for them).
    request_tracing: bool = True
    # trace_sample: fraction of requests whose trace is RECORDED (the
    # admission-time sampling decision; 1.0 default).  Unsampled
    # requests still carry ids end-to-end — only span recording and the
    # latency exemplar are skipped.
    trace_sample: float = 1.0

    # --- serving runtime (ours; README "Serving", lightgbm_tpu/serve) ---
    # serve_max_wait_ms: the coalescer's admission window — after the
    # first queued request, up to this many milliseconds of later arrivals
    # coalesce into the same bucket-rung batch (flushed EARLY the moment a
    # pow-2 rung fills).  Smaller = lower added latency, larger = fuller
    # batches under bursty load.
    serve_max_wait_ms: float = 2.0
    # serve_max_queue: admission bound on queued requests across the
    # runtime; submissions past it are SHED with a typed Overloaded error
    # (counted in serve_shed_total, evented, /healthz-visible) instead of
    # queuing unboundedly — a hang is never the failure mode.
    serve_max_queue: int = 1024
    # serve_slo_p99_ms: p99 latency SLO driving load shedding off the
    # existing predict_warm_latency_ms reservoirs — when the observed p99
    # exceeds this and requests are already queued, new submissions shed.
    # The reservoir is process-cumulative, so size the SLO for steady
    # state, not cold compiles (which never enter the warm reservoirs).
    # 0 (default) = no SLO shedding (queue bound + health shedding only).
    serve_slo_p99_ms: float = 0.0
    # serve_tenant_quota: per-tenant bound on queued requests (each served
    # model name is a tenant; per-tenant latency is labeled
    # serve_request_latency_ms{tenant="..."}).  A tenant at its quota
    # sheds with Overloaded while other tenants keep serving — one noisy
    # caller cannot monopolize the chip.  0 (default) = unlimited.
    serve_tenant_quota: int = 0
    # serve_replicas: replica count for the resilient fleet layer
    # (lightgbm_tpu/serve/fleet.py) — N dispatchers behind ONE admission
    # queue (one per device on a real slice; N threads off-chip), with
    # health-aware routing, an ejection/readmission circuit breaker and
    # watchdog-driven replica restart.  1 (default) keeps the solo
    # ServingRuntime unless another fleet knob opts in.
    serve_replicas: int = 1
    # serve_deadline_ms: per-request completion deadline — an admitted
    # request that cannot finish inside it raises a typed
    # DeadlineExceeded (distinct from Overloaded: admission succeeded,
    # completion was late; /predict maps it to 504).  Expired requests
    # still queued are dropped BEFORE spending a dispatch.  0 = off.
    serve_deadline_ms: float = 0.0
    # serve_hedge_ms: tail-latency hedging — a batch in flight on one
    # replica longer than this is speculatively re-dispatched on another
    # (first completion wins; predict is pure, so both produce the same
    # bits).  0 (default) = off; -1 = auto, p99-derived from the
    # serve_replica_batch_ms reservoirs.
    serve_hedge_ms: float = 0.0
    # serve_retry_budget: retry tokens added per admitted request (a
    # failed/dead/hung replica dispatch requeues its batch's requests
    # EXACTLY once onto a healthy replica, spending one token per
    # batch).  The budget is what turns a sick fleet into shedding
    # instead of a retry storm.  Negative = unlimited retries.
    serve_retry_budget: float = 0.25
    # serve_replica_trip: consecutive batch failures that trip a
    # replica's circuit breaker (ejected from rotation, readmitted via a
    # half-open probe after a jittered exponential cooldown).  The LAST
    # healthy replica is never ejected.
    serve_replica_trip: int = 3
    # serve_replica_cooldown_ms: base ejection cooldown; doubles per
    # consecutive trip, with +/-50% jitter.
    serve_replica_cooldown_ms: float = 50.0
    # serve_hang_timeout_ms: per-replica heartbeat staleness bound — a
    # replica holding a batch without a heartbeat tick for this long is
    # declared hung (serve_replica_hangs_total), its in-flight requests
    # requeue, and a replacement is spawned.  Size it above the worst
    # legitimate batch latency.
    serve_hang_timeout_ms: float = 2000.0
    # serve_restart_backoff_ms: base delay before a dead/hung replica's
    # replacement spawns; doubles per restart, jittered.  The
    # replacement warms the bucket ladder BEFORE joining rotation.
    serve_restart_backoff_ms: float = 20.0
    # serve_max_restarts: restarts per replica slot before it is
    # abandoned (the fleet degrades to the surviving replicas; the last
    # replica's death with no restarts left fails queued requests with a
    # typed error rather than hanging them).
    serve_max_restarts: int = 3

    # --- continual training (ours; README "Continuous training",
    # lightgbm_tpu/continual) ---
    # update_every_rows: the continual runner triggers an update
    # (leaf-value refit, escalating to appended trees) once this many
    # fresh rows have been ingested since the last rollover.  0 = no
    # row-driven updates (update_every_s or explicit update() calls
    # drive them).
    update_every_rows: int = 0
    # update_every_s: time-driven update trigger — an update fires when
    # the OLDEST un-incorporated ingested row is this many seconds old,
    # so a trickle of rows still reaches the model on a deadline.  0 =
    # no time-driven updates.
    update_every_s: float = 0.0
    # append_trees: trees appended per escalated continual update, seeded
    # init_model-style from the live ensemble (same growers, budgets and
    # bitwise semantics as offline continued training).  0 (default) =
    # refit-only: updates renew leaf values of the existing structure.
    append_trees: int = 0
    # drift_window: rows of recent ingest forming the rolling baseline
    # the per-chunk label-drift gauge (continual_label_drift) compares
    # against — the cheap covariate/label-shift signal riding the
    # continual_chunk event stream.
    drift_window: int = 8192
    # bin_cache_segment_threshold: durable-ingest append mode for
    # save_binary caches (io/stream.py).  0 (default) = every
    # append_rows() rewrites the whole cache (one file, O(total rows)
    # per append).  >= 1 = appends land in CRC'd sidecar segment files
    # (O(new rows) per append — the continual runner's steady-state
    # ingest cost) and the cache compacts back to one file once this
    # many live segments accumulate.
    bin_cache_segment_threshold: int = 0

    # --- booster fleets (ours; README "Booster fleets",
    # lightgbm_tpu/models/fleet.py) ---
    # fleet_size: expected number of boosters in a train_fleet batch.
    # 0 (default) = infer B from the (B, N) label matrix; a non-zero
    # value is a guard — train_fleet raises when it disagrees with the
    # labels, catching a transposed label matrix before a B=N fleet
    # trains silently.
    fleet_size: int = 0

    # unknown/passthrough params preserved here
    extra: Dict[str, Any] = field(default_factory=dict)
    # names the user explicitly set (vs defaults) — lets device-specific
    # default resolution (e.g. quantized training on wide-bin TPU runs)
    # respect an explicit user choice either way
    _explicit: set = field(default_factory=set, repr=False, compare=False)

    def is_set(self, name: str) -> bool:
        return name in self._explicit

    # ------------------------------------------------------------------
    @classmethod
    def from_dict(cls, params: Optional[Dict[str, Any]]) -> "Config":
        cfg = cls()
        cfg.update(params or {})
        return cfg

    def update(self, params: Dict[str, Any]) -> None:
        known = {f.name: f for f in fields(self)}
        for raw_key, value in params.items():
            key = _ALIASES.get(raw_key, raw_key)
            if key == "objective" and isinstance(value, str):
                value = canonical_objective(value)
            if key in known and key != "extra":
                cur = getattr(self, key)
                setattr(self, key, _coerce(value, cur, known[key].type))
                self._explicit.add(key)
            else:
                self.extra[key] = value
        # derived conveniences
        if self.objective in ("multiclass", "multiclassova") and self.num_class < 2:
            raise ValueError(
                "Number of classes should be specified and greater than 1 for multiclass training"
            )
        if self.tree_growth_mode not in ("auto", "strict", "rounds"):
            raise ValueError(
                f"tree_growth_mode must be auto/strict/rounds, got {self.tree_growth_mode!r}"
            )
        if self.hist_precision not in ("f32", "bf16"):
            raise ValueError(
                f"hist_precision must be f32/bf16, got {self.hist_precision!r}"
            )
        if self.max_bin >= 32768:
            # device bin storage is int16 (basic.py); the reference's uint16
            # caps at 65535 — far above any practical histogram width
            raise ValueError(f"max_bin must be < 32768, got {self.max_bin}")

    def to_dict(self) -> Dict[str, Any]:
        out = {}
        for f in fields(self):
            if f.name in ("extra", "_explicit"):
                continue
            out[f.name] = copy.deepcopy(getattr(self, f.name))
        out.update(self.extra)
        return out

    @property
    def num_tree_per_iteration(self) -> int:
        return self.num_class if self.objective in ("multiclass", "multiclassova") else 1

    # params that exist for CPU/GPU-implementation reasons and have no TPU
    # analogue (reference: every accepted param has semantics in
    # src/io/config_auto.cpp; here the honest equivalent is an explicit
    # warning whenever a non-default value would otherwise be silently
    # ignored — see docs/Parameters.md)
    _NA_PARAMS = {
        "force_col_wise": "histogram layout is chosen by the measured "
        "per-max_bin device strategy, not col/row-wise threading",
        "force_row_wise": "histogram layout is chosen by the measured "
        "per-max_bin device strategy, not col/row-wise threading",
        "histogram_pool_size": "per-leaf histograms live in device HBM; "
        "there is no host LRU histogram pool",
        "gpu_platform_id": "device selection is owned by JAX/XLA "
        "(JAX_PLATFORMS, jax.devices())",
        "gpu_device_id": "device selection is owned by JAX/XLA",
        "gpu_use_dp": "histogram accumulation precision is controlled by "
        "hist_precision (bf16x2/f32 lanes)",
        "num_gpu": "multi-device scale-out uses jax.sharding meshes via "
        "tree_learner=data|feature|voting|feature2d",
        "precise_float_parser": "parsing always uses full float64 "
        "precision (numpy)",
        "parser_config_file": "custom parser plugins are not supported",
    }

    def warn_na_params(self) -> None:
        """Warn for every accepted-but-N/A param set to a non-default value
        so nothing is silently ignored."""
        from .utils.log import log_warning

        defaults = type(self)()
        for name, reason in self._NA_PARAMS.items():
            if getattr(self, name) != getattr(defaults, name):
                log_warning(f"{name} has no effect on this backend: {reason}")


def _coerce(value: Any, current: Any, anno: Any) -> Any:
    """Coerce `value` to the type of the dataclass default (LightGBM accepts
    string-typed values everywhere since its config is string key=value)."""
    if isinstance(current, bool):
        if isinstance(value, str):
            return value.lower() in ("true", "1", "+", "yes")
        return bool(value)
    if isinstance(current, int) and not isinstance(value, bool):
        return int(value)
    if isinstance(current, float):
        return float(value)
    if isinstance(current, list):
        if isinstance(value, str):
            if not value:
                return []
            parts = [p for p in value.replace(" ", ",").split(",") if p]
            elem = (current[0] if current else None)
            if isinstance(elem, int):
                return [int(p) for p in parts]
            if isinstance(elem, float):
                return [float(p) for p in parts]
            # unknown element type: keep strings, try numeric
            out: List[Any] = []
            for p in parts:
                try:
                    out.append(int(p))
                except ValueError:
                    try:
                        out.append(float(p))
                    except ValueError:
                        out.append(p)
            return out
        if isinstance(value, (list, tuple)):
            return list(value)
        return [value]
    if isinstance(current, str):
        if isinstance(value, (list, tuple)):
            return ",".join(str(v) for v in value)
        return str(value)
    return value


def choose_param_value(main_param_name: str, params: Dict[str, Any], default_value: Any) -> Dict[str, Any]:
    """Resolve aliases in a raw param dict in favor of the main parameter
    (reference: python-package/lightgbm/basic.py -> _choose_param_value)."""
    params = dict(params)
    if main_param_name in params:
        return params
    for alias, canon in _ALIASES.items():
        if canon == main_param_name and alias in params:
            params[main_param_name] = params.pop(alias)
            return params
    if default_value is not None:
        params[main_param_name] = default_value
    return params
