"""Device mesh helpers.

TPU-native replacement for the reference's network bring-up
(reference: src/network/network.cpp Network::Init, linkers_socket.cpp —
machine lists, listen ports, full TCP mesh).  On TPU the SPMD context is a
jax.sharding.Mesh over the slice's chips; multi-host bring-up is
jax.distributed.initialize, and the collectives ride ICI/DCN via XLA.

The reference's network params (num_machines, machines, local_listen_port,
time_out, machine_list_filename) are accepted by the config layer and
translated: num_machines>1 simply asserts the mesh is large enough.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"  # rows (reference: tree_learner=data rank axis)
FEATURE_AXIS = "feature"  # feature blocks (reference: tree_learner=feature)

# nested two-level mesh axes (docs/DISTRIBUTED.md "Hierarchical merge"):
# ICI_AXIS ranks share a slice's chip interconnect — full histogram
# collectives are cheap there; DCN_AXIS crosses slices over data-center
# network, where only top-k-shaped or scalar operands may travel
# (jaxlint R17, jaxpr-audit dcn_max_bytes pin)
ICI_AXIS = "ici"
DCN_AXIS = "dcn"


def make_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D data mesh over the available chips."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def data_axis_size(mesh: Mesh) -> int:
    """Ranks along the data axis — the R in the sharded fused round's
    per-rank row/feature math (local rows = padded // R; the scatter
    merge pads F to a multiple of R)."""
    return int(mesh.shape[DATA_AXIS])


def make_mesh_2d(n_data: int, n_feature: int, devices: Optional[Sequence] = None) -> Mesh:
    """(data, feature) mesh for combined data+feature parallel histograms."""
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices[: n_data * n_feature]).reshape(n_data, n_feature)
    return Mesh(devices, (DATA_AXIS, FEATURE_AXIS))


def make_mesh_hierarchical(num_slices: int,
                           ranks_per_slice: Optional[int] = None,
                           devices: Optional[Sequence] = None) -> Mesh:
    """Nested (dcn, ici) mesh for multi-slice scale-out: ``num_slices``
    slice groups of ``ranks_per_slice`` chips each.  On a real multi-slice
    pod the outer axis crosses DCN (device order from the platform groups
    slices contiguously); on the loopback CPU mesh it simulates the slice
    boundary so the two-level merge's collective TOPOLOGY — full
    psum/psum_scatter over ``ici`` only, top-k-shaped exchange over
    ``dcn`` — is traceable and testable off-chip
    (parallel/hierarchy.py, docs/DISTRIBUTED.md "Hierarchical merge")."""
    if devices is None:
        devices = jax.devices()
    num_slices = int(num_slices)
    if num_slices < 1:
        raise ValueError(f"num_slices must be >= 1, got {num_slices}")
    if ranks_per_slice is None:
        if len(devices) % num_slices:
            raise ValueError(
                f"{len(devices)} devices do not divide into "
                f"{num_slices} slices")
        ranks_per_slice = len(devices) // num_slices
    devices = np.asarray(
        devices[: num_slices * ranks_per_slice]).reshape(
        num_slices, ranks_per_slice)
    return Mesh(devices, (DCN_AXIS, ICI_AXIS))


def slice_axis_sizes(mesh: Mesh) -> tuple:
    """(num_slices, ranks_per_slice) of a hierarchical mesh."""
    return int(mesh.shape[DCN_AXIS]), int(mesh.shape[ICI_AXIS])

