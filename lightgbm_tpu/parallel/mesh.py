"""Device mesh helpers.

TPU-native replacement for the reference's network bring-up
(reference: src/network/network.cpp Network::Init, linkers_socket.cpp —
machine lists, listen ports, full TCP mesh).  On TPU the SPMD context is a
jax.sharding.Mesh over the slice's chips; multi-host bring-up is
jax.distributed.initialize, and the collectives ride ICI/DCN via XLA.

The reference's network params (num_machines, machines, local_listen_port,
time_out, machine_list_filename) are accepted by the config layer and
translated: num_machines>1 simply asserts the mesh is large enough.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"  # rows (reference: tree_learner=data rank axis)
FEATURE_AXIS = "feature"  # feature blocks (reference: tree_learner=feature)


def make_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D data mesh over the available chips."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def data_axis_size(mesh: Mesh) -> int:
    """Ranks along the data axis — the R in the sharded fused round's
    per-rank row/feature math (local rows = padded // R; the scatter
    merge pads F to a multiple of R)."""
    return int(mesh.shape[DATA_AXIS])


def make_mesh_2d(n_data: int, n_feature: int, devices: Optional[Sequence] = None) -> Mesh:
    """(data, feature) mesh for combined data+feature parallel histograms."""
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices[: n_data * n_feature]).reshape(n_data, n_feature)
    return Mesh(devices, (DATA_AXIS, FEATURE_AXIS))

