"""JAX version compatibility for the SPMD learners.

The learners target the stable ``jax.shard_map(..., check_vma=...)`` API
(JAX >= 0.6).  On older toolchains (0.4.x, where shard_map lives in
``jax.experimental.shard_map`` and the kwarg is ``check_rep``) the wrapper
below translates — so the loopback distributed tests and the tier-1
sanitizer runs work on whichever JAX the container bakes in.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _sm

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma, **kw)
