"""Data-parallel tree learning over a device mesh.

TPU-native re-design of the reference's parallel tree learners
(reference: src/treelearner/data_parallel_tree_learner.cpp,
feature_parallel_tree_learner.cpp, voting_parallel_tree_learner.cpp and the
Network collectives they call — ReduceScatter of histogram buffers,
Allreduce(max-gain SplitInfo), GlobalSyncUpBySum).

Mapping (SURVEY.md §3.5):
  * rows sharded over the mesh DATA_AXIS (reference: pre_partition row split);
  * each shard histograms its local rows, then `jax.lax.psum` merges the
    (3, F, B) histogram across the axis — standing in for the reference's
    ReduceScatter + per-rank feature ownership.  Because every shard then
    holds the GLOBAL histogram, split finding is replicated and the
    SyncUpGlobalBestSplit Allreduce disappears entirely: all shards compute
    the same argmax deterministically.
  * per-row leaf ids stay shard-local; tree arrays come out replicated.

This collapses the reference's 3-collective-per-split protocol into one psum
per histogram — the right trade on ICI where bandwidth is plentiful and
latency dominates.  A psum_scatter + owned-feature variant (closer to the
reference at DCN scale) is the voting-parallel path's job.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.split import SplitParams
from ..ops.treegrow import TreeArrays, grow_tree
from .compat import shard_map
from .mesh import DATA_AXIS


class ShardedData:
    """Training arrays laid out over the mesh's data axis (rows padded to a
    multiple of the axis size; padding rows carry row_mask=0 so they never
    contribute to histograms)."""

    def __init__(self, mesh: Mesh, bins: np.ndarray, num_bins_pf: np.ndarray,
                 missing_bin_pf: np.ndarray, *, process_local: bool = False):
        """process_local=True (reference: pre_partition): `bins` holds only
        THIS process's rows; the global array is assembled from per-process
        shards (each process pads its share to a per-device multiple), so no
        rank ever materializes the full dataset."""
        self.mesh = mesh
        n, f = bins.shape
        self.n_devices = mesh.devices.size
        self.process_local = process_local and jax.process_count() > 1
        self.row_sharding = NamedSharding(mesh, P(DATA_AXIS))
        self.rep_sharding = NamedSharding(mesh, P())
        if self.process_local:
            local_dev = self.n_devices // jax.process_count()
            pad = (-n) % max(local_dev, 1)
            self.num_data = n  # LOCAL rows (Dataset holds the local shard)
            self.local_padded = n + pad
            self.padded = self.local_padded * jax.process_count()
        else:
            pad = (-n) % self.n_devices
            self.num_data = n
            self.padded = n + pad
            self.local_padded = self.padded
        if pad:
            bins = np.concatenate([bins, np.zeros((pad, f), bins.dtype)], axis=0)
        row_valid = np.zeros(self.local_padded, bool)
        row_valid[:n] = True
        self.bins = self._put_rows(bins)
        self.row_valid = self._put_rows(row_valid)
        self.num_bins_pf = jax.device_put(num_bins_pf, self.rep_sharding)
        self.missing_bin_pf = jax.device_put(missing_bin_pf, self.rep_sharding)

    def _put_rows(self, arr: np.ndarray) -> jnp.ndarray:
        if self.process_local:
            return jax.make_array_from_process_local_data(
                self.row_sharding, np.asarray(arr)
            )
        return jax.device_put(arr, self.row_sharding)

    def pad_rows(self, arr: np.ndarray, fill=0.0) -> jnp.ndarray:
        pad = self.local_padded - self.num_data
        if pad:
            arr = np.concatenate([np.asarray(arr), np.full((pad,) + np.shape(arr)[1:], fill, np.asarray(arr).dtype)])
        return self._put_rows(arr)

    def local_rows(self, global_arr) -> np.ndarray:
        """Extract THIS process's rows of a row-sharded global array
        (ordered by each shard's global offset), trimmed of padding."""
        shards = sorted(global_arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        out = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
        return out[: self.num_data]

    def pad_rows_device(self, arr, dtype, fill=0.0) -> jnp.ndarray:
        """Pad + reshard WITHOUT a host round-trip (the async rounds-grower
        path: grad/hess/masks are already device arrays)."""
        if self.process_local:
            # device_put with a global sharding would treat every rank's
            # [local, zeros] as the same global array and feed rank 1+ the
            # zero padding; go through the per-process assembly path (one
            # host hop — correctness over pipelining in multi-controller)
            return self.pad_rows(np.asarray(jnp.asarray(arr, dtype)), fill)
        arr = jnp.asarray(arr, dtype)
        pad = self.padded - self.num_data
        if pad:
            arr = jnp.concatenate(
                [arr, jnp.full((pad,) + arr.shape[1:], fill, dtype)]
            )
        return jax.device_put(arr, self.row_sharding)


@functools.lru_cache(maxsize=64)
def _sharded_grower(mesh, grower, extra_names: tuple, grower_kwargs: tuple):
    """Cached jitted shard_map wrapper around a grower function.  Cached so
    repeated boosting iterations reuse one trace/compile (the closure would
    otherwise key a fresh jit every call); shared by the strict and rounds
    growers so the shard_map plumbing cannot diverge."""
    kwargs = dict(grower_kwargs)

    def wrapped(bins, grad_, hess_, mask_, sw_, fmask_, nbpf_, mbpf_, *extras):
        return grower(
            bins, grad_, hess_, mask_, sw_, fmask_, nbpf_, mbpf_,
            **dict(zip(extra_names, extras)), **kwargs,
        )

    return jax.jit(
        shard_map(
            wrapped,
            mesh=mesh,
            in_specs=(
                P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                P(DATA_AXIS), P(), P(), P(),
            ) + tuple(P() for _ in extra_names),
            out_specs=(
                TreeArrays(*([P()] * len(TreeArrays._fields))),  # replicated
                P(DATA_AXIS),  # leaf_id
            ),
            check_vma=False,
        )
    )


def _run_sharded(sharded, grower, opt, grower_kwargs, grad, hess, row_mask,
                 sample_weight, feature_mask):
    extra_names = tuple(k for k, v in opt.items() if v is not None)
    extra_vals = tuple(opt[k] for k in extra_names)
    fn = _sharded_grower(sharded.mesh, grower, extra_names,
                         tuple(sorted(grower_kwargs.items())))
    return fn(
        sharded.bins, grad, hess, row_mask, sample_weight, feature_mask,
        sharded.num_bins_pf, sharded.missing_bin_pf, *extra_vals,
    )


def grow_tree_data_parallel(
    sharded: ShardedData,
    grad: jnp.ndarray,  # (Npad,) sharded over DATA_AXIS
    hess: jnp.ndarray,
    row_mask: jnp.ndarray,  # (Npad,) bool sharded — bagging AND validity
    sample_weight: jnp.ndarray,
    feature_mask: jnp.ndarray,  # (F,) replicated
    categorical_mask: Optional[jnp.ndarray] = None,
    monotone_constraints: Optional[jnp.ndarray] = None,
    interaction_sets: Optional[jnp.ndarray] = None,
    rng_key: Optional[jnp.ndarray] = None,  # replicated — identical per-node
    # sampling on every shard keeps the SPMD trees in lockstep
    feature_contri: Optional[jnp.ndarray] = None,  # (F,) replicated
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    hist_strategy: str = "auto",
    parallel_mode: str = "data",  # "data" or "voting" (rows sharded in both)
    top_k: int = 20,
    monotone_method: str = "basic",
) -> Tuple[TreeArrays, jnp.ndarray]:
    """SPMD tree growth: identical trees on every shard, shard-local leaf ids.

    reference call-stack analogue: DataParallelTreeLearner::Train (SURVEY.md
    §4.4) with psum in place of ReduceScatter/Allreduce.
    """
    opt = {
        "categorical_mask": categorical_mask,
        "monotone_constraints": monotone_constraints,
        "interaction_sets": interaction_sets,
        "rng_key": rng_key,
        "feature_contri": feature_contri,
    }
    kw = dict(
        num_leaves=num_leaves, num_bins=num_bins, max_depth=max_depth,
        params=params, hist_strategy=hist_strategy, axis_name=DATA_AXIS,
        parallel_mode=parallel_mode, top_k=top_k,
        monotone_method=monotone_method,
    )
    return _run_sharded(sharded, grow_tree, opt, kw, grad, hess, row_mask,
                        sample_weight, feature_mask)


def grow_tree_fast_data_parallel(
    sharded: ShardedData,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    row_mask: jnp.ndarray,
    sample_weight: jnp.ndarray,
    feature_mask: jnp.ndarray,
    categorical_mask: Optional[jnp.ndarray] = None,
    monotone_constraints: Optional[jnp.ndarray] = None,
    interaction_sets: Optional[jnp.ndarray] = None,
    rng_key: Optional[jnp.ndarray] = None,
    quant_key: Optional[jnp.ndarray] = None,
    cegb_feature_penalty: Optional[jnp.ndarray] = None,
    feature_contri: Optional[jnp.ndarray] = None,  # (F,) replicated
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    leaf_tile: int = 8,
    hist_precision: str = "f32",
    use_pallas: bool = True,
    quantize_bins: int = 0,
    stochastic_rounding: bool = True,
    quant_renew: bool = False,
    track_path: bool = False,
    monotone_method: str = "basic",
) -> Tuple[TreeArrays, jnp.ndarray]:
    """Round-batched grower under SPMD data parallelism: each shard runs the
    multi-leaf histogram pass over its rows, one psum per round merges the
    (tile, 3, F, B) block, and every shard applies the identical splits
    (reference analogue: DataParallelTreeLearner with the multi-leaf pass
    replacing per-split ReduceScatter rounds).  Intermediate monotone
    bounds work unchanged: leaf aggregates are psummed, so every shard's
    bound recomputation sees identical state."""
    from ..ops.treegrow_fast import grow_tree_fast

    opt = {
        "categorical_mask": categorical_mask,
        "monotone_constraints": monotone_constraints,
        "interaction_sets": interaction_sets,
        "rng_key": rng_key,
        "quant_key": quant_key,
        "cegb_feature_penalty": cegb_feature_penalty,
        "feature_contri": feature_contri,
    }
    kw = dict(
        num_leaves=num_leaves, num_bins=num_bins, max_depth=max_depth,
        params=params, axis_name=DATA_AXIS, leaf_tile=leaf_tile,
        hist_precision=hist_precision, use_pallas=use_pallas,
        quantize_bins=quantize_bins, stochastic_rounding=stochastic_rounding,
        quant_renew=quant_renew, track_path=track_path,
        monotone_method=monotone_method,
    )
    return _run_sharded(sharded, grow_tree_fast, opt, kw, grad, hess,
                        row_mask, sample_weight, feature_mask)


@functools.partial(jax.jit, static_argnames=("axis_name",))
def _psum_scalar(x, axis_name: str):
    return jax.lax.psum(x, axis_name)


@functools.lru_cache(maxsize=8)
def _metric_sums_fn(mesh: Mesh):
    """Cached per-mesh reduction jit: building it inline in
    distributed_metric_sums keyed a fresh trace every eval round (jaxlint R2)."""
    return jax.jit(
        shard_map(
            lambda l, w: (jax.lax.psum(l, DATA_AXIS), jax.lax.psum(w, DATA_AXIS)),
            mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def distributed_metric_sums(mesh: Mesh, local_loss_sum: jnp.ndarray, local_weight_sum: jnp.ndarray):
    """Distributed metric reduction (reference: Network::GlobalSyncUpBySum used
    by Metric::Eval in every distributed mode)."""
    return _metric_sums_fn(mesh)(local_loss_sum, local_weight_sum)
