"""Data-parallel tree learning over a device mesh.

TPU-native re-design of the reference's parallel tree learners
(reference: src/treelearner/data_parallel_tree_learner.cpp,
feature_parallel_tree_learner.cpp, voting_parallel_tree_learner.cpp and the
Network collectives they call — ReduceScatter of histogram buffers,
Allreduce(max-gain SplitInfo), GlobalSyncUpBySum).

Mapping (SURVEY.md §3.5):
  * rows sharded over the mesh DATA_AXIS (reference: pre_partition row split);
  * each shard histograms its local rows, then `jax.lax.psum` merges the
    (3, F, B) histogram across the axis — standing in for the reference's
    ReduceScatter + per-rank feature ownership.  Because every shard then
    holds the GLOBAL histogram, split finding is replicated and the
    SyncUpGlobalBestSplit Allreduce disappears entirely: all shards compute
    the same argmax deterministically.
  * per-row leaf ids stay shard-local; tree arrays come out replicated.

This collapses the reference's 3-collective-per-split protocol into one psum
per histogram — the right trade on ICI where bandwidth is plentiful and
latency dominates.  A psum_scatter + owned-feature variant (closer to the
reference at DCN scale) is the voting-parallel path's job.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.split import SplitParams
from ..ops.treegrow import TreeArrays, grow_tree
from .compat import shard_map
from .mesh import DATA_AXIS, data_axis_size


class ShardedData:
    """Training arrays laid out over the mesh's data axis (rows padded to a
    multiple of the axis size; padding rows carry row_mask=0 so they never
    contribute to histograms)."""

    def __init__(self, mesh: Mesh, bins: np.ndarray, num_bins_pf: np.ndarray,
                 missing_bin_pf: np.ndarray, *, process_local: bool = False):
        """process_local=True (reference: pre_partition): `bins` holds only
        THIS process's rows; the global array is assembled from per-process
        shards (each process pads its share to a per-device multiple), so no
        rank ever materializes the full dataset."""
        self.mesh = mesh
        n, f = bins.shape
        self.n_devices = mesh.devices.size
        self.process_local = process_local and jax.process_count() > 1
        self.row_sharding = NamedSharding(mesh, P(DATA_AXIS))
        self.rep_sharding = NamedSharding(mesh, P())
        if self.process_local:
            local_dev = self.n_devices // jax.process_count()
            pad = (-n) % max(local_dev, 1)
            self.num_data = n  # LOCAL rows (Dataset holds the local shard)
            self.local_padded = n + pad
            self.padded = self.local_padded * jax.process_count()
        else:
            pad = (-n) % self.n_devices
            self.num_data = n
            self.padded = n + pad
            self.local_padded = self.padded
        if pad:
            bins = np.concatenate([bins, np.zeros((pad, f), bins.dtype)], axis=0)
        row_valid = np.zeros(self.local_padded, bool)
        row_valid[:n] = True
        self.bins = self._put_rows(bins)
        self.row_valid = self._put_rows(row_valid)
        self.num_bins_pf = jax.device_put(num_bins_pf, self.rep_sharding)
        self.missing_bin_pf = jax.device_put(missing_bin_pf, self.rep_sharding)

    def _put_rows(self, arr: np.ndarray) -> jnp.ndarray:
        if self.process_local:
            return jax.make_array_from_process_local_data(
                self.row_sharding, np.asarray(arr)
            )
        return jax.device_put(arr, self.row_sharding)

    def pad_rows(self, arr: np.ndarray, fill=0.0) -> jnp.ndarray:
        pad = self.local_padded - self.num_data
        if pad:
            a = np.asarray(arr)  # convert ONCE; metadata reads off the binding
            arr = np.concatenate(
                [a, np.full((pad,) + a.shape[1:], fill, a.dtype)])
        return self._put_rows(arr)

    def local_rows(self, global_arr) -> np.ndarray:
        """Extract THIS process's rows of a row-sharded global array
        (ordered by each shard's global offset), trimmed of padding."""
        shards = sorted(global_arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        out = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
        return out[: self.num_data]

    def pad_rows_device(self, arr, dtype, fill=0.0) -> jnp.ndarray:
        """Pad + reshard WITHOUT a host round-trip (the async rounds-grower
        path: grad/hess/masks are already device arrays)."""
        if self.process_local:
            # device_put with a global sharding would treat every rank's
            # [local, zeros] as the same global array and feed rank 1+ the
            # zero padding; go through the per-process assembly path (one
            # host hop — correctness over pipelining in multi-controller)
            return self.pad_rows(np.asarray(jnp.asarray(arr, dtype)), fill)
        arr = jnp.asarray(arr, dtype)
        pad = self.padded - self.num_data
        if pad:
            arr = jnp.concatenate(
                [arr, jnp.full((pad,) + arr.shape[1:], fill, dtype)]
            )
        return jax.device_put(arr, self.row_sharding)

    def bins_t(self, f_pad: Optional[int] = None) -> jnp.ndarray:
        """Feature-major (F_pad, N_padded) device copy of the bins, rows
        sharded over the mesh data axis — the windowed grower's layout
        (column slices of (F, N) are ~20x cheaper than row gathers of
        (N, F); ops/treegrow_windowed.py).  ``f_pad`` zero-pads the
        feature dim (the psum_scatter merge needs F divisible by the axis
        size; pad features carry num_bins=1 and a False feature_mask so
        they can never win a split).  Built once device-side (a sharded
        transpose — XLA routes the all-to-all) and cached."""
        key = int(f_pad or 0)
        cache = getattr(self, "_bins_t_cache", None)
        if cache is None:
            cache = self._bins_t_cache = {}
        if key not in cache:
            f = self.bins.shape[1]
            cache[key] = _bins_t_builder(self.mesh, f, f_pad or f)(self.bins)
        return cache[key]


@functools.lru_cache(maxsize=16)
def _bins_t_builder(mesh: Mesh, f: int, f_pad: int):
    """Cached jitted sharded transpose (rows-sharded (N, F) -> rows-sharded
    feature-major (F_pad, N)) — one trace per (mesh, shape) config."""
    def t(b):
        bt = b.T
        if f_pad > f:
            bt = jnp.concatenate(
                [bt, jnp.zeros((f_pad - f, b.shape[0]), b.dtype)])
        return bt

    return jax.jit(t, out_shardings=NamedSharding(mesh, P(None, DATA_AXIS)))


@functools.lru_cache(maxsize=64)
def _sharded_grower(mesh, grower, extra_names: tuple, grower_kwargs: tuple):
    """Cached jitted shard_map wrapper around a grower function.  Cached so
    repeated boosting iterations reuse one trace/compile (the closure would
    otherwise key a fresh jit every call); shared by the strict and rounds
    growers so the shard_map plumbing cannot diverge."""
    kwargs = dict(grower_kwargs)

    def wrapped(bins, grad_, hess_, mask_, sw_, fmask_, nbpf_, mbpf_, *extras):
        return grower(
            bins, grad_, hess_, mask_, sw_, fmask_, nbpf_, mbpf_,
            **dict(zip(extra_names, extras)), **kwargs,
        )

    return jax.jit(
        shard_map(
            wrapped,
            mesh=mesh,
            in_specs=(
                P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                P(DATA_AXIS), P(), P(), P(),
            ) + tuple(P() for _ in extra_names),
            out_specs=(
                TreeArrays(*([P()] * len(TreeArrays._fields))),  # replicated
                P(DATA_AXIS),  # leaf_id
            ),
            check_vma=False,
        )
    )


def _run_sharded(sharded, grower, opt, grower_kwargs, grad, hess, row_mask,
                 sample_weight, feature_mask):
    extra_names = tuple(k for k, v in opt.items() if v is not None)
    extra_vals = tuple(opt[k] for k in extra_names)
    fn = _sharded_grower(sharded.mesh, grower, extra_names,
                         tuple(sorted(grower_kwargs.items())))
    return fn(
        sharded.bins, grad, hess, row_mask, sample_weight, feature_mask,
        sharded.num_bins_pf, sharded.missing_bin_pf, *extra_vals,
    )


def grow_tree_data_parallel(
    sharded: ShardedData,
    grad: jnp.ndarray,  # (Npad,) sharded over DATA_AXIS
    hess: jnp.ndarray,
    row_mask: jnp.ndarray,  # (Npad,) bool sharded — bagging AND validity
    sample_weight: jnp.ndarray,
    feature_mask: jnp.ndarray,  # (F,) replicated
    categorical_mask: Optional[jnp.ndarray] = None,
    monotone_constraints: Optional[jnp.ndarray] = None,
    interaction_sets: Optional[jnp.ndarray] = None,
    rng_key: Optional[jnp.ndarray] = None,  # replicated — identical per-node
    # sampling on every shard keeps the SPMD trees in lockstep
    feature_contri: Optional[jnp.ndarray] = None,  # (F,) replicated
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    hist_strategy: str = "auto",
    parallel_mode: str = "data",  # "data" or "voting" (rows sharded in both)
    top_k: int = 20,
    monotone_method: str = "basic",
) -> Tuple[TreeArrays, jnp.ndarray]:
    """SPMD tree growth: identical trees on every shard, shard-local leaf ids.

    reference call-stack analogue: DataParallelTreeLearner::Train (SURVEY.md
    §4.4) with psum in place of ReduceScatter/Allreduce.
    """
    opt = {
        "categorical_mask": categorical_mask,
        "monotone_constraints": monotone_constraints,
        "interaction_sets": interaction_sets,
        "rng_key": rng_key,
        "feature_contri": feature_contri,
    }
    kw = dict(
        num_leaves=num_leaves, num_bins=num_bins, max_depth=max_depth,
        params=params, hist_strategy=hist_strategy, axis_name=DATA_AXIS,
        parallel_mode=parallel_mode, top_k=top_k,
        monotone_method=monotone_method,
    )
    return _run_sharded(sharded, grow_tree, opt, kw, grad, hess, row_mask,
                        sample_weight, feature_mask)


def grow_tree_fast_data_parallel(
    sharded: ShardedData,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    row_mask: jnp.ndarray,
    sample_weight: jnp.ndarray,
    feature_mask: jnp.ndarray,
    categorical_mask: Optional[jnp.ndarray] = None,
    monotone_constraints: Optional[jnp.ndarray] = None,
    interaction_sets: Optional[jnp.ndarray] = None,
    rng_key: Optional[jnp.ndarray] = None,
    quant_key: Optional[jnp.ndarray] = None,
    cegb_feature_penalty: Optional[jnp.ndarray] = None,
    feature_contri: Optional[jnp.ndarray] = None,  # (F,) replicated
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    leaf_tile: int = 8,
    hist_precision: str = "f32",
    use_pallas: bool = True,
    quantize_bins: int = 0,
    stochastic_rounding: bool = True,
    quant_renew: bool = False,
    track_path: bool = False,
    monotone_method: str = "basic",
) -> Tuple[TreeArrays, jnp.ndarray]:
    """Round-batched grower under SPMD data parallelism: each shard runs the
    multi-leaf histogram pass over its rows, one psum per round merges the
    (tile, 3, F, B) block, and every shard applies the identical splits
    (reference analogue: DataParallelTreeLearner with the multi-leaf pass
    replacing per-split ReduceScatter rounds).  Intermediate monotone
    bounds work unchanged: leaf aggregates are psummed, so every shard's
    bound recomputation sees identical state."""
    from ..ops.treegrow_fast import grow_tree_fast

    opt = {
        "categorical_mask": categorical_mask,
        "monotone_constraints": monotone_constraints,
        "interaction_sets": interaction_sets,
        "rng_key": rng_key,
        "quant_key": quant_key,
        "cegb_feature_penalty": cegb_feature_penalty,
        "feature_contri": feature_contri,
    }
    kw = dict(
        num_leaves=num_leaves, num_bins=num_bins, max_depth=max_depth,
        params=params, axis_name=DATA_AXIS, leaf_tile=leaf_tile,
        hist_precision=hist_precision, use_pallas=use_pallas,
        quantize_bins=quantize_bins, stochastic_rounding=stochastic_rounding,
        quant_renew=quant_renew, track_path=track_path,
        monotone_method=monotone_method,
    )
    return _run_sharded(sharded, grow_tree_fast, opt, kw, grad, hess,
                        row_mask, sample_weight, feature_mask)


@functools.partial(jax.jit, static_argnames=("axis_name",))
def _psum_scalar(x, axis_name: str):
    return jax.lax.psum(x, axis_name)


@functools.lru_cache(maxsize=8)
def _metric_sums_fn(mesh: Mesh):
    """Cached per-mesh reduction jit: building it inline in
    distributed_metric_sums keyed a fresh trace every eval round (jaxlint R2)."""
    return jax.jit(
        shard_map(
            lambda l, w: (jax.lax.psum(l, DATA_AXIS), jax.lax.psum(w, DATA_AXIS)),
            mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def distributed_metric_sums(mesh: Mesh, local_loss_sum: jnp.ndarray, local_weight_sum: jnp.ndarray):
    """Distributed metric reduction (reference: Network::GlobalSyncUpBySum used
    by Metric::Eval in every distributed mode)."""
    return _metric_sums_fn(mesh)(local_loss_sum, local_weight_sum)


# ---------------------------------------------------------------------------
# sharded fused windowed rounds (docs/DISTRIBUTED.md "Sharded fused rounds")
#
# The one-dispatch windowed round (ops/treegrow_windowed.py) under SPMD:
# each rank histograms its LOCAL row shard's window and the leaf-histogram
# merge is a single collective INSIDE the already-donated dispatch — psum
# (merge="psum": replicated histograms + replicated split search, the ICI
# default) or psum_scatter (merge="scatter": owned-feature split search +
# in-dispatch winner election, the reference's ReduceScatter analogue).
# The host loop is the IDENTICAL async protocol (_run_fused_rounds): 1
# dispatch, 0 blocking syncs, 0 retraces per steady-state round PER RANK,
# with the 5-scalar info vector collective-merged on device so the
# one-round-behind W-ladder/whint/finite reads are rank-consistent.
# ---------------------------------------------------------------------------

def _windowed_state_spec(merge: str):
    from ..ops.split import BestSplit
    from ..ops.treegrow_windowed import WState

    hist = P() if merge == "psum" else P(None, None, DATA_AXIS, None)
    return WState(
        order=P(DATA_AXIS), leaf_start=P(DATA_AXIS), leaf_cnt=P(DATA_AXIS),
        leaf_id=P(DATA_AXIS), hist=hist,
        best=BestSplit(*([P()] * len(BestSplit._fields))),
        leaf_sum_g=P(), leaf_sum_h=P(), leaf_count=P(), leaf_depth=P(),
        leaf_parent=P(), leaf_side=P(), num_leaves_cur=P(), leaf_out=P(),
        tree=TreeArrays(*([P()] * len(TreeArrays._fields))),
    )


# per-optional-input sharding: row-indexed arrays ride the data axis,
# everything else is replicated
_WOPT_SPECS = {
    "gq": P(DATA_AXIS), "hq": P(DATA_AXIS), "quant_scale": P(),
    "rng_key": P(), "quant_key": P(), "feature_contri": P(),
    "categorical_mask": P(),
}


@functools.lru_cache(maxsize=32)
def _windowed_init_sharded(mesh: Mesh, merge: str, extra_names: tuple,
                           statics: tuple):
    from ..ops import treegrow_windowed as _tw

    kwargs = dict(statics)
    quant = bool(kwargs.get("quantize_bins"))

    def wrapped(bins_t, grad, hess, row_mask, sw, nbpf, mbpf, fmask, *extras):
        ex = dict(zip(extra_names, extras))
        return _tw._w_init.__wrapped__(
            bins_t, grad, hess, row_mask, sw, nbpf, mbpf, fmask,
            ex.get("rng_key"), ex.get("quant_key"), ex.get("feature_contri"),
            ex.get("categorical_mask"), None, None, None,
            axis_name=DATA_AXIS, merge=merge, **kwargs)

    state_spec = _windowed_state_spec(merge)
    row = P(DATA_AXIS)
    qspec = (row, row, P()) if quant else (None, None, None)
    return jax.jit(shard_map(
        wrapped, mesh=mesh,
        in_specs=(P(None, DATA_AXIS), row, row, row, row, P(), P(), P())
        + tuple(_WOPT_SPECS[n] for n in extra_names),
        out_specs=(state_spec, row, row) + qspec + (row, row),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=256)
def _windowed_round_sharded(mesh: Mesh, W: int, merge: str,
                            extra_names: tuple, statics: tuple):
    """One cached donated jit per (mesh, W-ladder rung, merge, statics) —
    the SPMD mirror of the single-device ladder's per-rung compiles."""
    from ..ops import treegrow_windowed as _tw

    kwargs = dict(statics)

    def wrapped(state, bins_t, grad, hess, row_mask, nbpf, mbpf, fmask,
                *extras):
        ex = dict(zip(extra_names, extras))
        return _tw._round_fused.__wrapped__(
            state, bins_t, grad, hess,
            ex.get("gq"), ex.get("hq"), ex.get("quant_scale"),
            row_mask, nbpf, mbpf, fmask,
            ex.get("rng_key"), ex.get("feature_contri"),
            ex.get("categorical_mask"), None, None, None,
            W=W, axis_name=DATA_AXIS, merge=merge, **kwargs)

    state_spec = _windowed_state_spec(merge)
    row = P(DATA_AXIS)
    return jax.jit(shard_map(
        wrapped, mesh=mesh,
        in_specs=(state_spec, P(None, DATA_AXIS), row, row, row,
                  P(), P(), P())
        + tuple(_WOPT_SPECS[n] for n in extra_names),
        out_specs=(state_spec, P()),  # info is collective-merged on device
        check_vma=False,
    ), donate_argnums=(0,))


@functools.lru_cache(maxsize=32)
def _windowed_finalize_sharded(mesh: Mesh, merge: str, statics: tuple):
    from ..ops import treegrow_windowed as _tw

    kwargs = dict(statics)

    def wrapped(state, grad_true, hess_true, row_mask):
        return _tw._w_finalize.__wrapped__(
            state, grad_true, hess_true, row_mask,
            axis_name=DATA_AXIS, **kwargs)

    row = P(DATA_AXIS)
    return jax.jit(shard_map(
        wrapped, mesh=mesh,
        in_specs=(_windowed_state_spec(merge), row, row, row),
        out_specs=(TreeArrays(*([P()] * len(TreeArrays._fields))), row),
        check_vma=False,
    ))


def _pad_features(v, f_pad: int, fill, sharding):
    """Pad a per-feature table to the scatter merge's F multiple (pad
    features are dead: num_bins=1, mask False — they can never win)."""
    if v is None:
        return None
    v = jnp.asarray(v)
    if v.shape[0] < f_pad:
        v = jnp.concatenate(
            [v, jnp.full((f_pad - v.shape[0],) + v.shape[1:], fill, v.dtype)])
    return jax.device_put(v, sharding)


def grow_tree_windowed_data_parallel(
    sharded: ShardedData,
    grad: jnp.ndarray,  # (Npad,) sharded over DATA_AXIS
    hess: jnp.ndarray,
    row_mask: jnp.ndarray,
    sample_weight: jnp.ndarray,
    feature_mask: jnp.ndarray,  # (F,) replicated
    categorical_mask: Optional[jnp.ndarray] = None,
    rng_key: Optional[jnp.ndarray] = None,
    quant_key: Optional[jnp.ndarray] = None,
    feature_contri: Optional[jnp.ndarray] = None,
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    leaf_tile: int = 16,
    hist_precision: str = "f32",
    use_pallas: bool = True,
    quantize_bins: int = 0,
    stochastic_rounding: bool = True,
    quant_renew: bool = False,
    merge: str = "psum",  # "psum" | "scatter" (owned-feature ReduceScatter)
    stats: Optional[dict] = None,
    guard_label: str = "",
    megakernel_opt: Optional[str] = None,
) -> Tuple[TreeArrays, jnp.ndarray]:
    """SPMD fused windowed growth: the flagship one-dispatch round over the
    ICI mesh.  Each steady-state round is ONE donated dispatch and ZERO
    blocking host syncs per rank (pinned by tests/test_retrace.py with the
    DispatchCounter, telemetry and tracing on); the histogram merge and the
    info-vector reduction both ride inside that dispatch.

    ``merge="scatter"`` pays when split search dominates (owned features
    parallelize it R-ways and the merge moves half the bytes) but requires
    deterministic replicated admission — it is refused with per-node
    feature sampling (feature_fraction_bynode/extra_trees), whose sampled
    set must span the full feature axis on every rank."""
    import os as _os

    from ..ops import treegrow_windowed as _tw
    from ..utils import degrade as _degrade

    if merge not in ("psum", "scatter"):
        raise ValueError(f"merge must be 'psum' or 'scatter', got {merge!r}")
    if merge == "scatter" and (
            rng_key is not None or params.feature_fraction_bynode < 1.0
            or params.extra_trees):
        raise ValueError(
            "merge='scatter' (owned-feature split search) is incompatible "
            "with per-node feature sampling (feature_fraction_bynode/"
            "extra_trees): each rank samples only its owned block; use "
            "merge='psum'")
    mesh = sharded.mesh
    n_dev = data_axis_size(mesh)
    f = int(sharded.num_bins_pf.shape[0])
    f_pad = (-(-f // n_dev) * n_dev) if merge == "scatter" else f
    rep = sharded.rep_sharding
    bins_t = sharded.bins_t(f_pad if f_pad != f else None)
    nbpf = _pad_features(sharded.num_bins_pf, f_pad, 1, rep)
    mbpf = _pad_features(sharded.missing_bin_pf, f_pad, -1, rep)
    fmask = _pad_features(jnp.asarray(feature_mask, bool), f_pad, False, rep)
    cmask = _pad_features(categorical_mask, f_pad, False, rep)
    fcontri = _pad_features(feature_contri, f_pad, 1.0, rep)

    use_pallas = bool(use_pallas and _degrade.available(_degrade.HIST))
    pallas_partition = use_pallas and (
        _os.environ.get("LGBMTPU_PARTITION_PALLAS", "1") != "0") and (
        _degrade.available(_degrade.PARTITION))
    # round megakernel (ops/round_pallas.py) under SPMD: each rank's
    # partition + window histogram is one fused kernel; the leaf-histogram
    # merge stays the round's single in-dispatch collective (psum /
    # psum_scatter below, UNCHANGED), so the split search runs post-merge
    # exactly as before.  Same envelope gate as the single-device entry.
    mk, mk_interp = _tw.megakernel_mode(use_pallas, rng_key=rng_key,
                                        efb_bins_t=None,
                                        quantize_bins=quantize_bins,
                                        mode=megakernel_opt)
    common = dict(num_leaves=num_leaves, num_bins=num_bins, params=params,
                  leaf_tile=leaf_tile)

    def _grow(megakernel: bool, mk_interpret: bool):
        init_statics = tuple(sorted(dict(
            common, use_pallas=use_pallas, quantize_bins=quantize_bins,
            hist_precision=hist_precision,
            stochastic_rounding=stochastic_rounding).items()))
        init_opt = {"rng_key": rng_key, "quant_key": quant_key,
                    "feature_contri": fcontri, "categorical_mask": cmask}
        init_names = tuple(k for k, v in init_opt.items() if v is not None)
        init_fn = _windowed_init_sharded(mesh, merge, init_names,
                                         init_statics)
        state, g_d, h_d, gq, hq, qs, g_true, h_true = init_fn(
            bins_t, grad, hess, row_mask, sample_weight, nbpf, mbpf, fmask,
            *(init_opt[k] for k in init_names))

        round_statics = tuple(sorted(dict(
            common, max_depth=max_depth, use_pallas=use_pallas,
            quantize_bins=quantize_bins, hist_precision=hist_precision,
            has_cat=categorical_mask is not None,
            pallas_partition=pallas_partition,
            megakernel=megakernel, mk_interpret=mk_interpret).items()))
        round_opt = {"gq": gq, "hq": hq, "quant_scale": qs,
                     "rng_key": rng_key, "feature_contri": fcontri,
                     "categorical_mask": cmask}
        round_names = tuple(k for k, v in round_opt.items()
                            if v is not None)
        round_vals = tuple(round_opt[k] for k in round_names)

        def round_fn(st, W):
            fn = _windowed_round_sharded(mesh, W, merge, round_names,
                                         round_statics)
            return fn(st, bins_t, g_d, h_d, row_mask, nbpf, mbpf, fmask,
                      *round_vals)

        # each rank's window is bounded by its LOCAL rows (the globally-
        # small child can hold all of one rank's rows of its ancestor —
        # the halving argument is global, so the local ladder starts at
        # the full shard)
        n_loc = sharded.padded // n_dev
        state = _tw._run_fused_rounds(
            round_fn, state, n_ladder=n_loc,
            w_first=_tw._window_size(max(n_loc, 1), n_loc),
            num_leaves=num_leaves, stats=stats, guard_label=guard_label)

        fin_statics = tuple(sorted(dict(
            params=params,
            quant_renew=bool(quant_renew and quantize_bins)).items()))
        fin = _windowed_finalize_sharded(mesh, merge, fin_statics)
        return fin(state, g_true, h_true, row_mask)

    if not mk:
        return _grow(False, False)
    if mk_interp:
        # correctness harness: registry ignored, failures surface (the
        # single-device entry's interpret contract)
        from ..utils import faults as _faults

        _faults.maybe_fail("pallas_round")
        return _grow(True, True)
    # the LAYERED degrade net, sharded edition: a megakernel failure at
    # compile/execute time disables ROUND and regrows this tree on the
    # three-pass sharded round from the ORIGINAL inputs (only internal
    # WState buffers were donated to the failed dispatch)
    return _degrade.run_with_fallback(
        _degrade.ROUND, lambda: _grow(True, False),
        lambda: _grow(False, False), fault_site="pallas_round")
