"""Feature-parallel tree learning over a device mesh.

TPU-native re-design of the reference's feature-parallel learner
(reference: src/treelearner/feature_parallel_tree_learner.cpp —
FeatureParallelTreeLearner<...>: every machine holds ALL rows, features are
partitioned; each finds the best split on its own features;
SyncUpGlobalBestSplit Allreduces the max-gain SplitInfo; all machines apply
the identical split locally).

Mapping (SURVEY.md §3.5 "TP-analog"):
  * the binned matrix is sharded on the FEATURE axis (columns), rows
    replicated — the model/width-dimension sharding of GBDT;
  * per-shard local best split -> `pmax` gain + lowest-rank winner broadcast
    (ops/treegrow.py mode="feature");
  * the partition decision for the winning feature is computed on its owner
    shard and broadcast with a psum — replacing the reference's "no row
    exchange needed because data is replicated" with one tiny collective.

Features are padded to a multiple of the axis size with trivial columns
(1 bin, never splittable), mirroring the reference's uneven feature
partition handling.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.split import SplitParams
from ..ops.treegrow import TreeArrays, grow_tree
from .compat import shard_map
from .mesh import DATA_AXIS


class FeatureShardedData:
    """Training arrays laid out with features sharded over the mesh axis."""

    def __init__(self, mesh: Mesh, bins: np.ndarray, num_bins_pf: np.ndarray,
                 missing_bin_pf: np.ndarray):
        self.mesh = mesh
        n, f = bins.shape
        self.n_devices = mesh.devices.size
        pad = (-f) % self.n_devices
        self.num_feature = f
        self.padded_f = f + pad
        if pad:
            # trivial pad features: constant bin 0, 1 bin, no missing stream
            bins = np.concatenate([bins, np.zeros((n, pad), bins.dtype)], axis=1)
            num_bins_pf = np.concatenate([num_bins_pf, np.ones(pad, np.int32)])
            missing_bin_pf = np.concatenate([missing_bin_pf, np.full(pad, -1, np.int32)])
        self.col_sharding = NamedSharding(mesh, P(None, DATA_AXIS))
        self.f_sharding = NamedSharding(mesh, P(DATA_AXIS))
        self.rep_sharding = NamedSharding(mesh, P())
        self.bins = jax.device_put(bins, self.col_sharding)
        self.num_bins_pf = jax.device_put(np.asarray(num_bins_pf, np.int32), self.f_sharding)
        self.missing_bin_pf = jax.device_put(np.asarray(missing_bin_pf, np.int32), self.f_sharding)

    def pad_features(self, arr: np.ndarray, fill=0) -> jnp.ndarray:
        """Pad a per-feature array and shard it over the mesh axis."""
        arr = np.asarray(arr)
        pad = self.padded_f - self.num_feature
        if pad:
            arr = np.concatenate([arr, np.full((pad,) + arr.shape[1:], fill, arr.dtype)])
        return jax.device_put(arr, self.f_sharding)

    def pad_sets(self, arr: np.ndarray) -> jnp.ndarray:
        """Pad interaction sets (S, F) on the feature axis and shard."""
        arr = np.asarray(arr)
        pad = self.padded_f - self.num_feature
        if pad:
            arr = np.concatenate(
                [arr, np.zeros((arr.shape[0], pad), arr.dtype)], axis=1
            )
        return jax.device_put(arr, NamedSharding(self.mesh, P(None, DATA_AXIS)))


@functools.lru_cache(maxsize=64)
def _fp_grower(mesh: Mesh, names: tuple, num_leaves: int, num_bins: int,
               max_depth: int, params: SplitParams, hist_strategy: str,
               monotone_method: str):
    """Cached jitted shard_map wrapper for feature-parallel growth: building
    the closure inline retraced EVERY boosting iteration (jaxlint R2); caching
    on (mesh, extras, static config) reuses one trace/compile, matching
    data_parallel._sharded_grower."""
    spec_of = {
        "categorical_mask": P(DATA_AXIS),
        "monotone_constraints": P(DATA_AXIS),
        "interaction_sets": P(None, DATA_AXIS),
        "rng_key": P(),
        "feature_contri": P(DATA_AXIS),
    }

    def wrapped(bins, grad_, hess_, mask_, sw_, fmask_, nbpf_, mbpf_, *extras):
        return grow_tree(
            bins, grad_, hess_, mask_, sw_, fmask_, nbpf_, mbpf_,
            **dict(zip(names, extras)),
            num_leaves=num_leaves,
            num_bins=num_bins,
            max_depth=max_depth,
            params=params,
            hist_strategy=hist_strategy,
            axis_name=DATA_AXIS,
            parallel_mode="feature",
            monotone_method=monotone_method,
        )

    return jax.jit(
        shard_map(
            wrapped,
            mesh=mesh,
            in_specs=(
                P(None, DATA_AXIS),  # bins: columns sharded
                P(),  # grad (replicated rows)
                P(),  # hess
                P(),  # row_mask
                P(),  # sample_weight
                P(DATA_AXIS),  # feature_mask
                P(DATA_AXIS),  # num_bins_pf
                P(DATA_AXIS),  # missing_bin_pf
            ) + tuple(spec_of[k] for k in names),
            out_specs=(
                TreeArrays(*([P()] * len(TreeArrays._fields))),  # replicated
                P(),  # leaf_id replicated (all shards hold all rows)
            ),
            check_vma=False,
        )
    )


def grow_tree_feature_parallel(
    sharded: FeatureShardedData,
    grad: jnp.ndarray,  # (N,) replicated
    hess: jnp.ndarray,
    row_mask: jnp.ndarray,  # (N,) bool replicated
    sample_weight: jnp.ndarray,
    feature_mask: jnp.ndarray,  # (F,) host array — padded+sharded here
    categorical_mask: Optional[jnp.ndarray] = None,
    monotone_constraints: Optional[jnp.ndarray] = None,
    interaction_sets: Optional[jnp.ndarray] = None,
    rng_key: Optional[jnp.ndarray] = None,
    feature_contri: Optional[jnp.ndarray] = None,  # (F,) host array
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    hist_strategy: str = "auto",
    monotone_method: str = "basic",
) -> Tuple[TreeArrays, jnp.ndarray]:
    """SPMD feature-parallel growth: identical trees on every shard.

    NOTE: per-node RNG (extra_trees / feature_fraction_bynode) uses the same
    key on every shard but operates on different feature blocks, so the
    node-level sampling stays consistent shard-locally — matching the
    reference where each machine samples only its own features.
    """
    mesh = sharded.mesh
    fmask = sharded.pad_features(np.asarray(feature_mask, bool), fill=False)
    opt = {}
    if categorical_mask is not None:
        opt["categorical_mask"] = sharded.pad_features(
            np.asarray(categorical_mask, bool), fill=False
        )
    if monotone_constraints is not None:
        opt["monotone_constraints"] = sharded.pad_features(
            np.asarray(monotone_constraints, np.int32), fill=0
        )
    if interaction_sets is not None:
        opt["interaction_sets"] = sharded.pad_sets(np.asarray(interaction_sets, bool))
    if rng_key is not None:
        opt["rng_key"] = rng_key
    if feature_contri is not None:
        opt["feature_contri"] = sharded.pad_features(
            np.asarray(feature_contri, np.float32), fill=0.0
        )
    names = list(opt.keys())
    vals = tuple(opt[k] for k in names)
    fn = _fp_grower(mesh, tuple(names), num_leaves, num_bins, max_depth,
                    params, hist_strategy, monotone_method)
    rep = sharded.rep_sharding
    return fn(
        sharded.bins,
        jax.device_put(grad, rep),
        jax.device_put(hess, rep),
        jax.device_put(row_mask, rep),
        jax.device_put(sample_weight, rep),
        fmask,
        sharded.num_bins_pf,
        sharded.missing_bin_pf,
        *vals,
    )
