"""Multi-host bring-up: the reference's socket machine-list handshake mapped
onto jax.distributed.

Reference: src/network/linkers_socket.cpp (Linkers::Construct — parse
machine list, rank by matching the local address, TCP handshake) and
include/LightGBM/network.h.  The TPU-native replacement: every process calls
`jax.distributed.initialize` against a coordinator (machine 0); afterwards
`jax.devices()` is the GLOBAL device list across hosts and the existing
`jax.sharding.Mesh` + shard_map learners run unchanged — XLA routes
collectives over ICI within a slice and DCN across hosts, replacing the
reference's hand-rolled Allreduce/ReduceScatter over TCP.  That includes
the sharded fused windowed round (docs/DISTRIBUTED.md "Sharded fused
rounds"): every process drives the identical one-dispatch round loop,
the in-dispatch psum/psum_scatter crosses the process boundary, and the
collective-merged info vector keeps each process's host-side W-ladder
decisions in lockstep without any extra synchronization.

Config mapping (reference: Config network params):
  machines / machine_list_filename : "host:port" entries, one per process;
    entry 0 is the coordinator
  num_machines                     : process count (must match entries)
  local_listen_port                : used to disambiguate rank when several
    processes share one host (host:port matching, like the reference)
  time_out (minutes)               : initialization timeout

Rank detection mirrors the reference's Linkers::Construct: the local rank is
the machine-list entry whose host is a local address AND whose port equals
local_listen_port; the LIGHTGBM_TPU_RANK env var overrides (for containers
whose local addresses are not in the list).
"""

from __future__ import annotations

import os
import socket
import time
from typing import List, Tuple

from ..utils.log import log_info, log_warning

_initialized = False


def _parse_machines(cfg) -> List[Tuple[str, int]]:
    raw = cfg.machines
    if not raw and cfg.machine_list_filename:
        lines = []
        with open(cfg.machine_list_filename) as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                # reference format: "host port" (Common::Split drops repeats)
                lines.append(":".join(line.split()))
        raw = ",".join(lines)
    out = []
    for entry in raw.replace("\n", ",").split(","):
        entry = entry.strip()
        if not entry:
            continue
        host, _, port = entry.partition(":")
        out.append((host, int(port) if port else cfg.local_listen_port))
    return out


def _local_addresses() -> set:
    names = {"localhost", "127.0.0.1", socket.gethostname()}
    try:
        names.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    return names


def detect_rank(cfg, machines: List[Tuple[str, int]]) -> int:
    env = os.environ.get("LIGHTGBM_TPU_RANK")
    if env is not None:
        return int(env)
    local = _local_addresses()
    for i, (host, port) in enumerate(machines):
        if host in local and port == cfg.local_listen_port:
            return i
    # host-only fallback is safe only when it is unambiguous (the reference
    # reports a port mismatch when several entries share this host)
    host_matches = [i for i, (host, _) in enumerate(machines) if host in local]
    if len(host_matches) == 1:
        return host_matches[0]
    if len(host_matches) > 1:
        raise ValueError(
            f"{len(host_matches)} machine-list entries match this host but "
            f"none matches local_listen_port={cfg.local_listen_port}; set "
            "local_listen_port per process or LIGHTGBM_TPU_RANK"
        )
    raise ValueError(
        "cannot determine this machine's rank: no machine-list entry matches "
        f"a local address ({sorted(local)}); set LIGHTGBM_TPU_RANK"
    )


def init_distributed(cfg) -> bool:
    """Bring up the multi-process JAX runtime from the reference's network
    params.  Returns True when a multi-host runtime is (already) active.
    Idempotent; a no-op for num_machines <= 1."""
    global _initialized
    if cfg.num_machines <= 1:
        return False
    if _initialized:
        return True
    import jax

    machines = _parse_machines(cfg)
    if len(machines) != cfg.num_machines:
        raise ValueError(
            f"num_machines={cfg.num_machines} but the machine list has "
            f"{len(machines)} entries"
        )
    rank = detect_rank(cfg, machines)
    host0, port0 = machines[0]
    coordinator = f"{host0}:{port0}"
    log_info(
        f"Initializing distributed runtime: rank {rank}/{cfg.num_machines}, "
        f"coordinator {coordinator}"
    )
    # bounded retry-with-backoff for the rendezvous phase: coordinator
    # bring-up races (rank 0 not listening yet, stale TIME_WAIT sockets,
    # transient DNS) are the dominant init failure class on real fleets
    # and are safe to retry — jax.distributed.initialize is all-or-nothing
    # before it succeeds (docs/ROBUSTNESS.md).  LGBMTPU_INIT_RETRIES=1
    # disables retries.
    attempts = max(int(os.environ.get("LGBMTPU_INIT_RETRIES", "3")), 1)
    init_timeout = max(cfg.time_out, 1) * 60
    for attempt in range(attempts):
        t0 = time.monotonic()
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=cfg.num_machines,
                process_id=rank,
                initialization_timeout=init_timeout,
            )
            break
        except (ValueError, TypeError):
            # bad address / bad config: deterministic, never retryable
            raise
        except Exception as e:  # noqa: BLE001 — last attempt re-raises
            # only FAST failures are the transient class worth retrying
            # (coordinator not listening yet, connection refused).  An
            # attempt that burned a large share of the rendezvous timeout
            # means every peer waited it out too — retrying would multiply
            # a multi-hour worst case instead of failing fast.
            elapsed = time.monotonic() - t0
            if attempt == attempts - 1 or elapsed >= 0.5 * init_timeout:
                raise
            delay = min(1.0 * (2 ** attempt), 15.0)
            log_warning(
                f"distributed init attempt {attempt + 1}/{attempts} failed "
                f"after {elapsed:.1f}s ({type(e).__name__}: {str(e)[:200]}); "
                f"retrying rendezvous in {delay:.1f}s")
            time.sleep(delay)
    _initialized = True
    log_info(
        f"Distributed runtime up: {jax.process_count()} processes, "
        f"{jax.device_count()} global devices"
    )
    return True
