"""Multi-slice scale-out: the hierarchical two-level merge
(docs/DISTRIBUTED.md "Hierarchical merge").

The sharded fused round (parallel/data_parallel.py) assumes ONE ICI mesh
where a full (tile, 3, F, B) histogram ``psum`` is cheap.  Crossing DCN
— multi-slice v5e, anything past one pod slice — breaks that assumption:
at Epsilon shape a full merge moves ~1.5 GB per round, and DCN bandwidth
is an order of magnitude below ICI.  This module maps the reference's
voting-parallel route (PV-Tree; src/treelearner/
voting_parallel_tree_learner.cpp — local top-k feature election, global
vote, histogram exchange for ONLY the elected features) onto a nested
(dcn, ici) mesh:

* **inside a slice** the round keeps its single in-dispatch merge —
  ``psum`` or ``psum_scatter`` over the ``ici`` axis, the J1 collective
  sequence unchanged per slice (the jaxpr-audit contracts
  ``windowed_round_hierarchical_{psum,voting}`` pin this against the
  legacy sharded round);
* **between slices** only top-k-shaped traffic crosses the ``dcn``
  axis: each slice elects its ``top_k_features`` best features per
  split candidate from its slice-local gains (reusing ops/split.py's
  gain-plane machinery), ships the k gain scalars + feature ids
  (all_gather), and after a deterministic global vote ships ONLY the
  elected k features' histogram columns (psum) — so the per-round DCN
  byte bill is ≤ k histograms' worth per candidate, provable statically
  (jaxpr-audit ``dcn_max_bytes``; jaxlint R17 bans any full-F histogram
  operand on the dcn axis);
* everything stays inside the ONE donated dispatch: the 5-scalar async
  info vector and the window-child election merge across BOTH axes in
  the same trace, so the 1-dispatch/0-sync/0-retrace budget holds per
  rank exactly as on the single-level mesh (tests/test_hierarchy.py).

``WState.hist`` lives in SLICE domain under the two-level merge (each
slice's row-sum; sibling subtraction is closed per slice), sharded over
the dcn axis of the state spec, so no full-F histogram is ever
replicated — or moved — across slices.

When ``top_k_features`` covers every candidate feature the election is
exhaustive and the grown tree is structurally EXACT vs the single-mesh
sharded round (the global vote set is sorted ascending, so argmax
tie-breaks match the flat search bit-for-bit); smaller k is the
PV-Tree approximation, like the reference's ``top_k``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.split import (BestSplit, KMIN_SCORE, SplitParams, find_best_split,
                         gain_plane)
from ..ops.treegrow import TreeArrays
from .compat import shard_map
from .mesh import DCN_AXIS, ICI_AXIS, slice_axis_sizes


# ---------------------------------------------------------------------------
# the device-side two-phase election (called from _round_fused's trace)
# ---------------------------------------------------------------------------

def dcn_topk_best(
    cand_hists: jnp.ndarray,  # (C, 3, Fd, B) SLICE-domain candidate hists
    parent_g: jnp.ndarray,    # (C,) GLOBAL parent stats (replicated)
    parent_h: jnp.ndarray,
    parent_c: jnp.ndarray,
    num_bins_pf: jnp.ndarray,      # (Fd,) this rank's feature tables
    missing_bin_pf: jnp.ndarray,
    feature_mask: Optional[jnp.ndarray],
    categorical_mask: Optional[jnp.ndarray],
    feature_contri: Optional[jnp.ndarray],
    *,
    params: SplitParams,
    top_k: int,
    dcn_axis: str,
    depth: Optional[jnp.ndarray] = None,       # (C,)
    parent_out: Optional[jnp.ndarray] = None,  # (C,)
) -> BestSplit:
    """The hierarchical split search, entirely in-dispatch.

    Phase A (vote): per candidate, evaluate the full gain plane on the
    SLICE-local histograms with SLICE-local parent stats (summed from the
    candidate's own histogram — any feature's bins sum to the child's
    slice totals) and take each feature's best gain; ``top_k`` of those
    (gain scalars + feature ids) are all_gathered over the dcn axis.

    Phase B (elect + exchange): every slice deterministically scores the
    gathered votes (sum of valid local gains per feature; ``top_k``
    winners, ids sorted ascending so a full-coverage election reproduces
    the flat search's tie-breaks), gathers ONLY the elected features'
    histogram columns, psums them over dcn — the one histogram-shaped
    DCN collective, ≤ k features' worth per candidate — and runs the
    exact split selection on the now-GLOBAL k-feature histograms with
    the global parent stats.  The winner's feature index is mapped back
    to this rank's feature domain, so the caller's owned-feature
    ``_merge_best`` election (scatter merges) composes unchanged.

    Feature tables here are the caller's rank-local tables: full F under
    the intra-slice psum merge, the owned F/R block under scatter — the
    vote/exchange always stays inside one rank's feature domain, which
    is what keeps the dcn operands top-k-shaped (jaxlint R17)."""
    C, _, fd, _b = cand_hists.shape
    k = max(1, min(top_k, fd))  # top_k is a jit static (a Python int)
    if depth is None:
        depth = jnp.zeros_like(parent_g)
    if parent_out is None:
        parent_out = jnp.zeros_like(parent_g)
    depth = depth.astype(jnp.float32)

    # --- phase A: slice-local per-feature gains -------------------------
    # slice-local child totals from feature 0's bins (every window row
    # lands in exactly one bin per feature — pad features included, whose
    # rows all sit in bin 0 — so any feature's sum is the child total)
    loc = jnp.sum(cand_hists[:, :, 0, :], axis=2)  # (C, 3)

    def _local_fgain(h, pg, ph, pc, d, po):
        g, _ = gain_plane(
            h, pg, ph, pc, num_bins_pf, missing_bin_pf, params,
            feature_mask=feature_mask, categorical_mask=categorical_mask,
            depth=d, parent_output=po, feature_contri=feature_contri)
        return jnp.max(g, axis=1)  # (Fd,) best gain per feature

    fgain = jax.vmap(_local_fgain)(
        cand_hists, loc[:, 0], loc[:, 1], loc[:, 2], depth, parent_out)

    vote_gain, vote_idx = jax.lax.top_k(fgain, k)  # (C, k)
    all_gain = jax.lax.all_gather(vote_gain, dcn_axis)  # (S, C, k)
    all_idx = jax.lax.all_gather(vote_idx, dcn_axis)    # (S, C, k)

    # --- phase B: deterministic global vote + k-feature exchange --------
    # score = sum of VALID local gains per voted feature (dead votes —
    # gain KMIN — contribute nothing, exactly like unvoted features);
    # top_k ties break to the lowest feature id (stable), and the elected
    # set is sorted ascending so full coverage (k >= Fd) reproduces the
    # flat search's candidate order bit-for-bit
    contrib = jnp.where(all_gain > KMIN_SCORE / 2, all_gain, 0.0)
    c_idx = jnp.broadcast_to(
        jnp.arange(C, dtype=jnp.int32)[None, :, None], all_idx.shape)
    score = jnp.zeros((C, fd), jnp.float32).at[c_idx, all_idx].add(contrib)
    g_idx = jnp.sort(jax.lax.top_k(score, k)[1].astype(jnp.int32), axis=1)

    sub = jnp.take_along_axis(
        cand_hists, g_idx[:, None, :, None], axis=2)  # (C, 3, k, B)
    # THE histogram-shaped DCN collective: k features' columns per
    # candidate — never the full-F plane (jaxlint R17's whole point)
    sub = jax.lax.psum(sub, dcn_axis)

    opt = {}
    if feature_mask is not None:
        opt["feature_mask"] = feature_mask[g_idx]
    if categorical_mask is not None:
        opt["categorical_mask"] = categorical_mask[g_idx]
    if feature_contri is not None:
        opt["feature_contri"] = feature_contri[g_idx]

    def _best_one(h, nb, mb, pg, ph, pc, d, po, feature_mask=None,
                  categorical_mask=None, feature_contri=None):
        return find_best_split(
            h, pg, ph, pc, nb, mb, params, feature_mask=feature_mask,
            categorical_mask=categorical_mask, depth=d, parent_output=po,
            feature_contri=feature_contri)

    bb = jax.vmap(_best_one)(
        sub, num_bins_pf[g_idx], missing_bin_pf[g_idx],
        parent_g, parent_h, parent_c, depth, parent_out, **opt)
    # winner feature back to this rank's feature domain
    feat = jnp.take_along_axis(
        g_idx, bb.feature[:, None].astype(jnp.int32), axis=1)[:, 0]
    return bb._replace(feature=feat)


# ---------------------------------------------------------------------------
# nested-mesh data layout
# ---------------------------------------------------------------------------

_ROW_SPEC = P((DCN_AXIS, ICI_AXIS))


class SlicedData:
    """Training arrays laid out over the nested (dcn, ici) mesh: rows
    sharded over BOTH axes (slice-major — the slice's contiguous row
    block splits over its ici ranks), per-feature tables replicated.
    The hierarchical twin of parallel/data_parallel.py::ShardedData."""

    def __init__(self, mesh: Mesh, bins: np.ndarray, num_bins_pf: np.ndarray,
                 missing_bin_pf: np.ndarray):
        self.mesh = mesh
        self.num_slices, self.ranks_per_slice = slice_axis_sizes(mesh)
        n, f = bins.shape
        self.n_devices = mesh.devices.size
        self.row_sharding = NamedSharding(mesh, _ROW_SPEC)
        self.rep_sharding = NamedSharding(mesh, P())
        pad = (-n) % self.n_devices
        self.num_data = n
        self.padded = n + pad
        if pad:
            bins = np.concatenate(
                [bins, np.zeros((pad, f), bins.dtype)], axis=0)
        row_valid = np.zeros(self.padded, bool)
        row_valid[:n] = True
        self.bins = jax.device_put(bins, self.row_sharding)
        self.row_valid = jax.device_put(row_valid, self.row_sharding)
        self.num_bins_pf = jax.device_put(num_bins_pf, self.rep_sharding)
        self.missing_bin_pf = jax.device_put(missing_bin_pf,
                                             self.rep_sharding)

    @classmethod
    def from_sharded(cls, mesh: Mesh, sharded) -> "SlicedData":
        """Build from an already device-resident flat-mesh
        :class:`~..data_parallel.ShardedData` WITHOUT a second host
        upload of the bin matrix: the nested (dcn, ici) row layout over
        the same device order places byte-identical per-device blocks as
        the flat `P(data)` layout (both pad to the device-count multiple
        and split dim 0 contiguously), so the ``device_put`` reshard is
        an alias, not a copy — the booster keeps ONE device copy of the
        dominant array while both meshes stay usable (models/gbdt.py
        builds the flat layout first for the non-windowed fallback
        growers)."""
        if getattr(sharded, "process_local", False):
            raise ValueError(
                "SlicedData.from_sharded requires a single-controller "
                "ShardedData (pre_partition multi-controller is not "
                "wired through the hierarchical path)")
        self = cls.__new__(cls)
        self.mesh = mesh
        self.num_slices, self.ranks_per_slice = slice_axis_sizes(mesh)
        self.n_devices = mesh.devices.size
        if sharded.padded % self.n_devices:
            raise ValueError(
                f"flat layout padded to {sharded.padded} rows does not "
                f"cover {self.n_devices} nested-mesh devices")
        self.row_sharding = NamedSharding(mesh, _ROW_SPEC)
        self.rep_sharding = NamedSharding(mesh, P())
        self.num_data = sharded.num_data
        self.padded = sharded.padded
        self.bins = jax.device_put(sharded.bins, self.row_sharding)
        self.row_valid = jax.device_put(sharded.row_valid,
                                        self.row_sharding)
        self.num_bins_pf = jax.device_put(sharded.num_bins_pf,
                                          self.rep_sharding)
        self.missing_bin_pf = jax.device_put(sharded.missing_bin_pf,
                                             self.rep_sharding)
        return self

    def pad_rows(self, arr: np.ndarray, fill=0.0) -> jnp.ndarray:
        pad = self.padded - self.num_data
        if pad:
            a = np.asarray(arr)
            arr = np.concatenate(
                [a, np.full((pad,) + a.shape[1:], fill, a.dtype)])
        return jax.device_put(np.asarray(arr), self.row_sharding)

    def pad_rows_device(self, arr, dtype, fill=0.0) -> jnp.ndarray:
        arr = jnp.asarray(arr, dtype)
        pad = self.padded - self.num_data
        if pad:
            arr = jnp.concatenate(
                [arr, jnp.full((pad,) + arr.shape[1:], fill, dtype)])
        return jax.device_put(arr, self.row_sharding)

    def bins_t(self, f_pad: Optional[int] = None) -> jnp.ndarray:
        """Feature-major (F_pad, N_padded) copy, rows sharded over both
        mesh axes; cached per f_pad (see ShardedData.bins_t)."""
        key = int(f_pad or 0)
        cache = getattr(self, "_bins_t_cache", None)
        if cache is None:
            cache = self._bins_t_cache = {}
        if key not in cache:
            f = self.bins.shape[1]
            cache[key] = _bins_t_builder_hier(
                self.mesh, f, f_pad or f)(self.bins)
        return cache[key]


@functools.lru_cache(maxsize=16)
def _bins_t_builder_hier(mesh: Mesh, f: int, f_pad: int):
    def t(b):
        bt = b.T
        if f_pad > f:
            bt = jnp.concatenate(
                [bt, jnp.zeros((f_pad - f, b.shape[0]), b.dtype)])
        return bt

    return jax.jit(
        t, out_shardings=NamedSharding(mesh, P(None, (DCN_AXIS, ICI_AXIS))))


# ---------------------------------------------------------------------------
# jit(shard_map) builders over the nested mesh
# ---------------------------------------------------------------------------

def _hier_state_spec(merge: str):
    from ..ops.treegrow_windowed import WState

    # hist is SLICE-domain: each slice's full-F sum under the psum merge
    # (replicated over ici, distinct per slice -> sharded over dcn along
    # F), the owned F/R block under scatter (distinct per rank -> sharded
    # over both axes along F).  Never replicated across slices: no full-F
    # histogram exists globally, by layout.
    hist = (P(None, None, DCN_AXIS, None) if merge == "psum"
            else P(None, None, (DCN_AXIS, ICI_AXIS), None))
    row = _ROW_SPEC
    return WState(
        order=row, leaf_start=row, leaf_cnt=row, leaf_id=row, hist=hist,
        best=BestSplit(*([P()] * len(BestSplit._fields))),
        leaf_sum_g=P(), leaf_sum_h=P(), leaf_count=P(), leaf_depth=P(),
        leaf_parent=P(), leaf_side=P(), num_leaves_cur=P(), leaf_out=P(),
        tree=TreeArrays(*([P()] * len(TreeArrays._fields))),
    )


_HOPT_SPECS = {
    "gq": _ROW_SPEC, "hq": _ROW_SPEC, "quant_scale": P(),
    "quant_key": P(), "feature_contri": P(), "categorical_mask": P(),
}


@functools.lru_cache(maxsize=32)
def _windowed_init_hier(mesh: Mesh, merge: str, top_k: int,
                        extra_names: tuple, statics: tuple):
    from ..ops import treegrow_windowed as _tw

    kwargs = dict(statics)
    quant = bool(kwargs.get("quantize_bins"))

    def wrapped(bins_t, grad, hess, row_mask, sw, nbpf, mbpf, fmask,
                *extras):
        ex = dict(zip(extra_names, extras))
        return _tw._w_init.__wrapped__(
            bins_t, grad, hess, row_mask, sw, nbpf, mbpf, fmask,
            None, ex.get("quant_key"), ex.get("feature_contri"),
            ex.get("categorical_mask"), None, None, None,
            axis_name=ICI_AXIS, merge=merge, dcn_axis_name=DCN_AXIS,
            dcn_top_k=top_k, **kwargs)

    state_spec = _hier_state_spec(merge)
    row = _ROW_SPEC
    qspec = (row, row, P()) if quant else (None, None, None)
    return jax.jit(shard_map(
        wrapped, mesh=mesh,
        in_specs=(P(None, (DCN_AXIS, ICI_AXIS)), row, row, row, row,
                  P(), P(), P())
        + tuple(_HOPT_SPECS[n] for n in extra_names),
        out_specs=(state_spec, row, row) + qspec + (row, row),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=256)
def _windowed_round_hier(mesh: Mesh, W: int, merge: str, top_k: int,
                         extra_names: tuple, statics: tuple):
    """One cached donated jit per (mesh, W rung, merge, top_k, statics) —
    the nested-mesh mirror of data_parallel._windowed_round_sharded."""
    from ..ops import treegrow_windowed as _tw

    kwargs = dict(statics)

    def wrapped(state, bins_t, grad, hess, row_mask, nbpf, mbpf, fmask,
                *extras):
        ex = dict(zip(extra_names, extras))
        return _tw._round_fused.__wrapped__(
            state, bins_t, grad, hess,
            ex.get("gq"), ex.get("hq"), ex.get("quant_scale"),
            row_mask, nbpf, mbpf, fmask,
            None, ex.get("feature_contri"),
            ex.get("categorical_mask"), None, None, None,
            W=W, axis_name=ICI_AXIS, merge=merge, dcn_axis_name=DCN_AXIS,
            dcn_top_k=top_k, **kwargs)

    state_spec = _hier_state_spec(merge)
    row = _ROW_SPEC
    return jax.jit(shard_map(
        wrapped, mesh=mesh,
        in_specs=(state_spec, P(None, (DCN_AXIS, ICI_AXIS)), row, row, row,
                  P(), P(), P())
        + tuple(_HOPT_SPECS[n] for n in extra_names),
        out_specs=(state_spec, P()),  # info is collective-merged on device
        check_vma=False,
    ), donate_argnums=(0,))


@functools.lru_cache(maxsize=32)
def _windowed_finalize_hier(mesh: Mesh, merge: str, statics: tuple):
    from ..ops import treegrow_windowed as _tw

    kwargs = dict(statics)

    def wrapped(state, grad_true, hess_true, row_mask):
        return _tw._w_finalize.__wrapped__(
            state, grad_true, hess_true, row_mask,
            axis_name=ICI_AXIS, dcn_axis_name=DCN_AXIS, **kwargs)

    row = _ROW_SPEC
    return jax.jit(shard_map(
        wrapped, mesh=mesh,
        in_specs=(_hier_state_spec(merge), row, row, row),
        out_specs=(TreeArrays(*([P()] * len(TreeArrays._fields))), row),
        check_vma=False,
    ))


def _pad_features(v, f_pad: int, fill, sharding):
    if v is None:
        return None
    v = jnp.asarray(v)
    if v.shape[0] < f_pad:
        v = jnp.concatenate(
            [v, jnp.full((f_pad - v.shape[0],) + v.shape[1:], fill,
                         v.dtype)])
    return jax.device_put(v, sharding)


def grow_tree_windowed_hierarchical(
    sliced: SlicedData,
    grad: jnp.ndarray,  # (Npad,) sharded over (dcn, ici)
    hess: jnp.ndarray,
    row_mask: jnp.ndarray,
    sample_weight: jnp.ndarray,
    feature_mask: jnp.ndarray,  # (F,) replicated
    categorical_mask: Optional[jnp.ndarray] = None,
    quant_key: Optional[jnp.ndarray] = None,
    feature_contri: Optional[jnp.ndarray] = None,
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    leaf_tile: int = 16,
    hist_precision: str = "f32",
    use_pallas: bool = True,
    quantize_bins: int = 0,
    stochastic_rounding: bool = True,
    quant_renew: bool = False,
    merge: str = "psum",  # intra-slice: "psum" | "scatter"
    top_k_features: int = 32,
    stats: Optional[dict] = None,
    guard_label: str = "",
) -> Tuple[TreeArrays, jnp.ndarray]:
    """SPMD fused windowed growth over the nested (dcn, ici) mesh: each
    steady-state round is ONE donated dispatch and ZERO blocking host
    syncs per rank, the intra-slice histogram merge rides ``merge`` over
    the ici axis unchanged, and only top-k-shaped traffic crosses dcn
    (module docstring).  Same host loop, same W-ladder protocol, same
    telemetry as the single-level sharded entry.

    Per-node feature sampling is refused for BOTH merges here: the
    slice-local vote must be deterministic and identical across slices,
    which a per-slice sampled feature set breaks (the single-level
    scatter merge's refusal, widened to the election)."""
    from ..ops import treegrow_windowed as _tw
    from ..utils import degrade as _degrade

    if merge not in ("psum", "scatter"):
        raise ValueError(f"merge must be 'psum' or 'scatter', got {merge!r}")
    if params.feature_fraction_bynode < 1.0 or params.extra_trees:
        raise ValueError(
            "the hierarchical two-level merge is incompatible with "
            "per-node feature sampling (feature_fraction_bynode/"
            "extra_trees): the slice-local top-k vote must be "
            "deterministic and slice-consistent")
    if int(top_k_features) < 1:
        raise ValueError(
            f"top_k_features must be >= 1, got {top_k_features}")
    mesh = sliced.mesh
    n_ici = sliced.ranks_per_slice
    f = int(sliced.num_bins_pf.shape[0])
    f_pad = (-(-f // n_ici) * n_ici) if merge == "scatter" else f
    rep = sliced.rep_sharding
    bins_t = sliced.bins_t(f_pad if f_pad != f else None)
    nbpf = _pad_features(sliced.num_bins_pf, f_pad, 1, rep)
    mbpf = _pad_features(sliced.missing_bin_pf, f_pad, -1, rep)
    fmask = _pad_features(jnp.asarray(feature_mask, bool), f_pad, False, rep)
    cmask = _pad_features(categorical_mask, f_pad, False, rep)
    fcontri = _pad_features(feature_contri, f_pad, 1.0, rep)
    top_k = int(top_k_features)

    use_pallas = bool(use_pallas and _degrade.available(_degrade.HIST))
    common = dict(num_leaves=num_leaves, num_bins=num_bins, params=params,
                  leaf_tile=leaf_tile)

    init_statics = tuple(sorted(dict(
        common, use_pallas=use_pallas, quantize_bins=quantize_bins,
        hist_precision=hist_precision,
        stochastic_rounding=stochastic_rounding).items()))
    init_opt = {"quant_key": quant_key, "feature_contri": fcontri,
                "categorical_mask": cmask}
    init_names = tuple(k for k, v in init_opt.items() if v is not None)
    init_fn = _windowed_init_hier(mesh, merge, top_k, init_names,
                                  init_statics)
    state, g_d, h_d, gq, hq, qs, g_true, h_true = init_fn(
        bins_t, grad, hess, row_mask, sample_weight, nbpf, mbpf, fmask,
        *(init_opt[k] for k in init_names))

    round_statics = tuple(sorted(dict(
        common, max_depth=max_depth, use_pallas=use_pallas,
        quantize_bins=quantize_bins, hist_precision=hist_precision,
        has_cat=categorical_mask is not None,
        # the Pallas partition + round megakernel stay off the
        # hierarchical path until wired under the nested mesh (the
        # hist kernels still run via use_pallas)
        pallas_partition=False, megakernel=False,
        mk_interpret=False).items()))
    round_opt = {"gq": gq, "hq": hq, "quant_scale": qs,
                 "feature_contri": fcontri, "categorical_mask": cmask}
    round_names = tuple(k for k, v in round_opt.items() if v is not None)
    round_vals = tuple(round_opt[k] for k in round_names)

    def round_fn(st, W):
        fn = _windowed_round_hier(mesh, W, merge, top_k, round_names,
                                  round_statics)
        return fn(st, bins_t, g_d, h_d, row_mask, nbpf, mbpf, fmask,
                  *round_vals)

    # each rank's window is bounded by its LOCAL rows (see the sharded
    # entry: the halving argument is global, the ladder local)
    n_loc = sliced.padded // sliced.n_devices
    state = _tw._run_fused_rounds(
        round_fn, state, n_ladder=n_loc,
        w_first=_tw._window_size(max(n_loc, 1), n_loc),
        num_leaves=num_leaves, stats=stats, guard_label=guard_label)

    fin_statics = tuple(sorted(dict(
        params=params,
        quant_renew=bool(quant_renew and quantize_bins)).items()))
    fin = _windowed_finalize_hier(mesh, merge, fin_statics)
    return fin(state, g_true, h_true, row_mask)
