"""2-D (feature x row) sharded fused windowed rounds — the wide-F regime.

docs/DISTRIBUTED.md "2-D sharding".  Data-parallel and voting shard rows,
the hierarchical merge shards slices; this layer shards FEATURES too
(reference: src/treelearner/feature_parallel_tree_learner.cpp — each
machine owns a feature subset and finds its local best split — composed
with the data-parallel row split, i.e. the reference's "data+feature"
grid the voting learner approximates).  The bin matrix is laid out
``P(feature, row)`` over a named 2-D mesh (SNIPPETS.md [3]'s GSPMD
pattern): each device owns an ``(F/d_f, N/d_r)`` tile, so

* per-leaf window histograms are COMPLETE for the owned feature block by
  layout — the merge is the row-axis psum alone, with ZERO collective
  over the feature axis (pinned by jaxlint R20 + the
  ``windowed_round_2d_*`` jaxpr contracts);
* the split election reuses the scatter merge's owned-feature winner
  machinery (ops/treegrow_windowed.py::_split_tables/_merge_best) with
  the feature axis as the owning axis;
* the winner's go/no-go row decisions — computable only on the owner
  block — are one psum-broadcast ``(N_loc,)`` bool over the feature
  axis, the round's ONLY feature-axis data exchange; partition
  movements stay row-local.

The host loop is the IDENTICAL async protocol (_run_fused_rounds): the
5-scalar info vector, W-ladder, and 1-dispatch/0-sync/0-retrace budget
per rank ride unchanged (tests/test_feature2d.py pins the budget with
telemetry + tracing ON).

Composition hook: ``_round_fused`` takes ``feature_axis_name`` alongside
``dcn_axis_name``, so a 3-axis (dcn, feature, row) mesh is a builder +
spec away — the jaxpr audit's per-axis byte accounting was built to pin
it (analysis/jaxpr_audit.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.split import SplitParams
from ..ops.treegrow import TreeArrays
from .compat import shard_map
from .data_parallel import _WOPT_SPECS, _pad_features
from .mesh import DATA_AXIS, FEATURE_AXIS

MERGE_2D = "psum"  # the feature2d histogram merge is always the row psum
# (scatter would re-shard the already-feature-complete histograms)


def feature2d_axis_sizes(mesh: Mesh) -> Tuple[int, int]:
    """(d_row, d_feature) of a 2-D mesh."""
    return int(mesh.shape[DATA_AXIS]), int(mesh.shape[FEATURE_AXIS])


class Sharded2DData:
    """Training arrays laid out over the 2-D (data, feature) mesh.

    Rows pad to a multiple of d_row (padding rows carry row_valid=0 so
    they never contribute to histograms); features pad to a multiple of
    d_feature with DEAD features — num_bins=1, missing_bin=-1, a False
    feature_mask — exactly like the scatter merge's F padding, so a
    padded feature can never win a split and feature_fraction sampling
    can never draw it (the mask zeroes it out of the search).  The bin
    matrix lives feature-major as the ``(F_pad, N_pad)`` tile grid
    ``P(feature, row)``; row-indexed vectors ride ``P(data)`` (replicated
    across the feature axis); per-feature tables are replicated — the
    owned-feature search dynamic-slices its block in-trace, sharing the
    scatter merge's code path."""

    def __init__(self, mesh: Mesh, bins: np.ndarray, num_bins_pf: np.ndarray,
                 missing_bin_pf: np.ndarray):
        self.mesh = mesh
        d_r, d_f = feature2d_axis_sizes(mesh)
        n, f = bins.shape
        self.n_row_shards = d_r
        self.n_feature_shards = d_f
        self.num_data = n
        self.num_features = f
        self.padded = n + ((-n) % d_r)
        self.f_pad = f + ((-f) % d_f)
        self.row_sharding = NamedSharding(mesh, P(DATA_AXIS))
        self.rep_sharding = NamedSharding(mesh, P())
        self.tile_sharding = NamedSharding(mesh, P(FEATURE_AXIS, DATA_AXIS))
        bt = np.zeros((self.f_pad, self.padded), bins.dtype)
        bt[:f, :n] = bins.T  # pad features read bin 0 for every row (dead)
        self.bins_t = jax.device_put(bt, self.tile_sharding)
        row_valid = np.zeros(self.padded, bool)
        row_valid[:n] = True
        self.row_valid = jax.device_put(row_valid, self.row_sharding)
        self.num_bins_pf = _pad_features(
            num_bins_pf, self.f_pad, 1, self.rep_sharding)
        self.missing_bin_pf = _pad_features(
            missing_bin_pf, self.f_pad, -1, self.rep_sharding)

    def pad_rows_device(self, arr, dtype, fill=0.0) -> jnp.ndarray:
        """Pad + lay a row vector over the row axis (replicated across the
        feature axis) without a host round-trip."""
        arr = jnp.asarray(arr, dtype)
        pad = self.padded - self.num_data
        if pad:
            arr = jnp.concatenate(
                [arr, jnp.full((pad,) + arr.shape[1:], fill, dtype)])
        return jax.device_put(arr, self.row_sharding)


def _2d_state_spec():
    """WState layout on the 2-D mesh: row bookkeeping is per-ROW-rank
    (replicated across feature blocks), histograms are per-FEATURE-block
    (complete for the owned features, replicated across row ranks after
    the row psum), and decisions/tree are fully replicated."""
    from ..ops.split import BestSplit
    from ..ops.treegrow_windowed import WState

    row = P(DATA_AXIS)
    return WState(
        order=row, leaf_start=row, leaf_cnt=row, leaf_id=row,
        hist=P(None, None, FEATURE_AXIS, None),
        best=BestSplit(*([P()] * len(BestSplit._fields))),
        leaf_sum_g=P(), leaf_sum_h=P(), leaf_count=P(), leaf_depth=P(),
        leaf_parent=P(), leaf_side=P(), num_leaves_cur=P(), leaf_out=P(),
        tree=TreeArrays(*([P()] * len(TreeArrays._fields))),
    )


@functools.lru_cache(maxsize=32)
def _windowed_init_2d(mesh: Mesh, extra_names: tuple, statics: tuple):
    from ..ops import treegrow_windowed as _tw

    kwargs = dict(statics)
    quant = bool(kwargs.get("quantize_bins"))

    def wrapped(bins_t, grad, hess, row_mask, sw, nbpf, mbpf, fmask, *extras):
        ex = dict(zip(extra_names, extras))
        return _tw._w_init.__wrapped__(
            bins_t, grad, hess, row_mask, sw, nbpf, mbpf, fmask,
            ex.get("rng_key"), ex.get("quant_key"), ex.get("feature_contri"),
            ex.get("categorical_mask"), None, None, None,
            axis_name=DATA_AXIS, merge=MERGE_2D,
            feature_axis_name=FEATURE_AXIS, **kwargs)

    state_spec = _2d_state_spec()
    row = P(DATA_AXIS)
    qspec = (row, row, P()) if quant else (None, None, None)
    return jax.jit(shard_map(
        wrapped, mesh=mesh,
        in_specs=(P(FEATURE_AXIS, DATA_AXIS), row, row, row, row,
                  P(), P(), P())
        + tuple(_WOPT_SPECS[n] for n in extra_names),
        out_specs=(state_spec, row, row) + qspec + (row, row),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=256)
def _windowed_round_2d(mesh: Mesh, W: int, extra_names: tuple,
                       statics: tuple):
    """One cached donated jit per (mesh, W-ladder rung, statics) — the 2-D
    mirror of data_parallel._windowed_round_sharded."""
    from ..ops import treegrow_windowed as _tw

    kwargs = dict(statics)

    def wrapped(state, bins_t, grad, hess, row_mask, nbpf, mbpf, fmask,
                *extras):
        ex = dict(zip(extra_names, extras))
        return _tw._round_fused.__wrapped__(
            state, bins_t, grad, hess,
            ex.get("gq"), ex.get("hq"), ex.get("quant_scale"),
            row_mask, nbpf, mbpf, fmask,
            ex.get("rng_key"), ex.get("feature_contri"),
            ex.get("categorical_mask"), None, None, None,
            W=W, axis_name=DATA_AXIS, merge=MERGE_2D,
            feature_axis_name=FEATURE_AXIS, **kwargs)

    state_spec = _2d_state_spec()
    row = P(DATA_AXIS)
    return jax.jit(shard_map(
        wrapped, mesh=mesh,
        in_specs=(state_spec, P(FEATURE_AXIS, DATA_AXIS), row, row, row,
                  P(), P(), P())
        + tuple(_WOPT_SPECS[n] for n in extra_names),
        out_specs=(state_spec, P()),  # info is collective-merged on device
        check_vma=False,
    ), donate_argnums=(0,))


@functools.lru_cache(maxsize=32)
def _windowed_finalize_2d(mesh: Mesh, statics: tuple):
    from ..ops import treegrow_windowed as _tw

    kwargs = dict(statics)

    def wrapped(state, grad_true, hess_true, row_mask):
        return _tw._w_finalize.__wrapped__(
            state, grad_true, hess_true, row_mask,
            axis_name=DATA_AXIS, feature_axis_name=FEATURE_AXIS, **kwargs)

    row = P(DATA_AXIS)
    return jax.jit(shard_map(
        wrapped, mesh=mesh,
        in_specs=(_2d_state_spec(), row, row, row),
        out_specs=(TreeArrays(*([P()] * len(TreeArrays._fields))), row),
        check_vma=False,
    ))


def grow_tree_windowed_feature2d(
    sharded: Sharded2DData,
    grad: jnp.ndarray,  # (Npad,) over DATA_AXIS, replicated @feature
    hess: jnp.ndarray,
    row_mask: jnp.ndarray,
    sample_weight: jnp.ndarray,
    feature_mask: jnp.ndarray,  # (F,) replicated
    categorical_mask: Optional[jnp.ndarray] = None,
    rng_key: Optional[jnp.ndarray] = None,
    quant_key: Optional[jnp.ndarray] = None,
    feature_contri: Optional[jnp.ndarray] = None,
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    leaf_tile: int = 16,
    hist_precision: str = "f32",
    use_pallas: bool = True,
    quantize_bins: int = 0,
    stochastic_rounding: bool = True,
    quant_renew: bool = False,
    stats: Optional[dict] = None,
    guard_label: str = "",
) -> Tuple[TreeArrays, jnp.ndarray]:
    """Fused windowed growth over the 2-D (feature, row) mesh: each
    steady-state round is ONE donated dispatch and ZERO blocking host
    syncs per rank, the histogram phase crosses the feature axis with
    ZERO collectives, and the trees are structurally EXACT vs the
    single-device grower (tests/test_feature2d.py parity matrix).

    Like the scatter merge, the owned-feature split search requires the
    sampled feature set to span the full axis deterministically on every
    rank — per-node feature sampling is refused."""
    from ..ops import treegrow_windowed as _tw
    from ..utils import degrade as _degrade

    if (rng_key is not None or params.feature_fraction_bynode < 1.0
            or params.extra_trees):
        raise ValueError(
            "tree_learner=feature2d (owned-feature split search) is "
            "incompatible with per-node feature sampling "
            "(feature_fraction_bynode/extra_trees): each feature block "
            "searches only its owned features; use tree_learner=data")
    mesh = sharded.mesh
    f_pad = sharded.f_pad
    rep = sharded.rep_sharding
    bins_t = sharded.bins_t
    nbpf = sharded.num_bins_pf
    mbpf = sharded.missing_bin_pf
    fmask = _pad_features(jnp.asarray(feature_mask, bool), f_pad, False, rep)
    cmask = _pad_features(categorical_mask, f_pad, False, rep)
    fcontri = _pad_features(feature_contri, f_pad, 1.0, rep)

    use_pallas = bool(use_pallas and _degrade.available(_degrade.HIST))
    common = dict(num_leaves=num_leaves, num_bins=num_bins, params=params,
                  leaf_tile=leaf_tile)

    init_statics = tuple(sorted(dict(
        common, use_pallas=use_pallas, quantize_bins=quantize_bins,
        hist_precision=hist_precision,
        stochastic_rounding=stochastic_rounding).items()))
    init_opt = {"quant_key": quant_key, "feature_contri": fcontri,
                "categorical_mask": cmask}
    init_names = tuple(k for k, v in init_opt.items() if v is not None)
    init_fn = _windowed_init_2d(mesh, init_names, init_statics)
    state, g_d, h_d, gq, hq, qs, g_true, h_true = init_fn(
        bins_t, grad, hess, row_mask, sample_weight, nbpf, mbpf, fmask,
        *(init_opt[k] for k in init_names))

    # the megakernel stops before the collective merge and assumes the
    # full-F bin matrix per rank; it stays off the 2-D mesh until its
    # owned-block variant lands (mirrors the hierarchical entry)
    round_statics = tuple(sorted(dict(
        common, max_depth=max_depth, use_pallas=use_pallas,
        quantize_bins=quantize_bins, hist_precision=hist_precision,
        has_cat=categorical_mask is not None,
        pallas_partition=False, megakernel=False,
        mk_interpret=False).items()))
    round_opt = {"gq": gq, "hq": hq, "quant_scale": qs,
                 "feature_contri": fcontri, "categorical_mask": cmask}
    round_names = tuple(k for k, v in round_opt.items() if v is not None)
    round_vals = tuple(round_opt[k] for k in round_names)

    def round_fn(st, W):
        fn = _windowed_round_2d(mesh, W, round_names, round_statics)
        return fn(st, bins_t, g_d, h_d, row_mask, nbpf, mbpf, fmask,
                  *round_vals)

    # W bounds each ROW rank's local window (the feature axis replicates
    # rows, so the ladder domain is the row shard — same bound as the
    # 1-D sharded entry)
    n_loc = sharded.padded // sharded.n_row_shards
    state = _tw._run_fused_rounds(
        round_fn, state, n_ladder=n_loc,
        w_first=_tw._window_size(max(n_loc, 1), n_loc),
        num_leaves=num_leaves, stats=stats, guard_label=guard_label)

    fin_statics = tuple(sorted(dict(
        params=params,
        quant_renew=bool(quant_renew and quantize_bins)).items()))
    fin = _windowed_finalize_2d(mesh, fin_statics)
    return fin(state, g_true, h_true, row_mask)
