"""Multi-process training launcher — the Dask-orchestration analogue.

Reference: python-package/lightgbm/dask.py (~1,700 LoC): align partitions to
workers, find open ports, build the `machines` list, inject
num_machines/local_listen_port/tree_learner, run plain `lightgbm.train` on
every worker with network params, return the rank-0 model.

TPU-native redesign: workers are local processes wired through
`jax.distributed` (parallel/distributed.py maps the reference's machine-list
handshake onto the coordinator bring-up).  Each worker receives ONLY its row
shard (`pre_partition` semantics: bin boundaries sync from the global
sample, the global device array is assembled from process-local shards, and
no rank ever materializes the full dataset).  Every rank ends up with the
identical model; the launcher returns rank 0's.

eval_set support (reference: dask.py _train accepts eval_set and evaluates
per-worker): each eval set is row-sharded across ranks exactly like the
training data; workers build valid Datasets against the train shard's
binner and evaluate through the pre_partition synced metric path
(models/gbdt.py::_eval_at_synced — Network::GlobalSyncUpBySum analogue),
so every rank sees identical metric values and early stopping fires
identically everywhere.

This launcher is the single-host (loopback) form; on a real multi-host pod
run one worker per host with the same `machines` list — the worker body is
ordinary `lightgbm_tpu.train`, exactly like the reference's `_train_part`.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import _ALIASES, Config
from ..obs import metrics as _obs
from ..obs import trace as _trace
from ..utils import checkpoint as _checkpoint
from ..utils.log import log_warning

_WORKER_SRC = r"""
import os, sys, json
sys.path.insert(0, os.environ["LGBM_TPU_REPO"])
import numpy as np
from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.distributed import init_distributed

shard = np.load(os.environ["LGBM_TPU_SHARD"], allow_pickle=True)
net = {k: shard[k].item() for k in ("num_machines", "machines",
                                    "local_listen_port", "time_out")}
rank = os.environ["LIGHTGBM_TPU_RANK"]
# multi-slice fleets (docs/ROBUSTNESS.md "Slice-granular recovery"): the
# rendezvous rank is slice-LOCAL (each slice is its own collective
# world) while the worker id is fleet-GLOBAL — model outputs, acks and
# shard fingerprints key on the global id
wid = os.environ.get("LGBM_TPU_WORKER_ID", rank)

# per-rank metrics flight recorder (docs/OBSERVABILITY.md "Fleet
# metrics"): atomic snapshot writes start BEFORE the rendezvous and
# repeat every period, so even a rank that dies mid-round leaves a
# mergeable file for the launcher's fleet_metrics.json
from lightgbm_tpu.obs import metrics as _obs_metrics

_snap_path = os.environ.get("LGBMTPU_METRICS_SNAPSHOT_FILE")
if _snap_path:
    _obs_metrics.start_periodic_snapshots(
        _snap_path,
        float(os.environ.get("LGBMTPU_METRICS_SNAPSHOT_PERIOD_S", "1.0")))

if int(net["num_machines"]) > 1:
    # a 1-rank fleet skips the multi-process runtime entirely (the
    # simulated-rank recovery tests drive every launcher/checkpoint path
    # this way on containers whose jax lacks multiproc collectives)
    assert init_distributed(Config.from_dict(net))

import lightgbm_tpu as lgb

params = dict(np.load(os.environ["LGBM_TPU_PARAMS"], allow_pickle=True)[
    "params"].item())
params.update(net)
params["pre_partition"] = int(net["num_machines"]) > 1
if int(net["num_machines"]) > 1:
    params.setdefault("tree_learner", "data")
_cache = os.environ.get("LGBM_TPU_CACHE")
if _cache:
    # rank-sharded cache feed (docs/DISTRIBUTED.md): this worker reads
    # ONLY its row shard of one shared save_binary cache through
    # BinCacheStream(shard=) — ingest scales with the fleet instead of
    # every rank decompressing the full matrix
    _lo, _hi, _pad = (int(t) for t in
                      os.environ["LGBM_TPU_CACHE_SHARD"].split(","))
    ds = lgb.Dataset(
        _cache, params=dict(params, bin_cache_shard=(_lo, _hi, _pad)))
else:
    ds = lgb.Dataset(
        shard["X"],
        label=shard["y"],
        weight=(shard["w"] if shard["w"].size > 0 else None),
        group=(shard["g"] if "g" in shard and shard["g"].size > 0 else None),
    )
valid_sets, valid_names = [], []
n_eval = int(shard["n_eval"].item()) if "n_eval" in shard else 0
for i in range(n_eval):
    valid_sets.append(lgb.Dataset(
        shard[f"ev{i}_X"],
        label=shard[f"ev{i}_y"],
        weight=(shard[f"ev{i}_w"] if shard[f"ev{i}_w"].size > 0 else None),
        group=(shard[f"ev{i}_g"] if shard[f"ev{i}_g"].size > 0 else None),
        reference=ds,
    ))
    valid_names.append(str(shard[f"ev{i}_name"].item()))
callbacks = []
evals_result = {}
es_rounds = int(os.environ.get("LGBM_TPU_ES_ROUNDS", "0"))
if es_rounds > 0 and valid_sets:
    callbacks.append(lgb.early_stopping(es_rounds, verbose=False))
if valid_sets:
    callbacks.append(lgb.record_evaluation(evals_result))
if os.environ.get("LGBMTPU_FAULT"):
    # worker_death injection site (utils/faults.py): rank-gated hard exit
    # at the start of a chosen iteration — the scenario the launcher
    # watchdog exists to catch
    from lightgbm_tpu.utils import faults as _faults

    def _fault_cb(env):
        _faults.maybe_crash("worker_death", env.iteration + 1)
    _fault_cb.before_iteration = True
    _fault_cb.order = -100
    callbacks.append(_fault_cb)

# coordinated fleet checkpoints (docs/ROBUSTNESS.md "Elastic fleet
# recovery"): every ckpt_freq GLOBAL iterations rank 0 writes the
# fleet snapshot + manifest through utils/checkpoint.py and every other
# rank drops its sha-carrying ack — the round becomes resumable only
# once ALL ranks confirmed, so a crash anywhere in the window leaves the
# previous fleet-valid round authoritative
_ckpt_dir = os.environ.get("LGBMTPU_FLEET_CKPT_DIR")
_ckpt_freq = int(os.environ.get("LGBMTPU_FLEET_SNAPSHOT_FREQ", "0") or 0)
if _ckpt_dir and _ckpt_freq > 0:
    from lightgbm_tpu.utils import checkpoint as _ckpt

    _world = int(os.environ.get("LGBMTPU_FLEET_WORLD",
                                str(net["num_machines"])))
    _keep = int(os.environ.get("LGBMTPU_FLEET_SNAPSHOT_KEEP", "0") or 0)
    _rank_i = int(wid)  # manifest roles/acks key on the GLOBAL id
    _slices = json.loads(os.environ.get("LGBMTPU_FLEET_SLICES", "{}")) or None
    _shards = {}
    _shards_json = os.environ.get("LGBMTPU_FLEET_SHARDS_JSON")
    if _shards_json and os.path.exists(_shards_json):
        with open(_shards_json) as fh:
            _shards = json.load(fh)

    def _fleet_ckpt_cb(env):
        it = env.model.current_iteration()  # GLOBAL iteration: resumed
        if it % _ckpt_freq:                 # runs keep the numbering
            return
        text = env.model.model_to_string(raw_deltas=True)
        if _rank_i == 0:
            _ckpt.write_fleet_checkpoint(_ckpt_dir, text, it, _world,
                                         _shards, keep=_keep,
                                         slices=_slices)
        else:
            _ckpt.confirm_fleet_checkpoint(_ckpt_dir, it, _rank_i, text)
    _fleet_ckpt_cb.order = 100
    callbacks.append(_fleet_ckpt_cb)

bst = lgb.train(params, ds, int(os.environ["LGBM_TPU_ROUNDS"]),
                valid_sets=valid_sets or None,
                valid_names=valid_names or None,
                callbacks=callbacks,
                # resume-to-round relaunch: the launcher hands a restarted
                # fleet the newest fleet-VALID manifest; engine.train
                # verifies it (incl. this rank's shard fingerprint) and
                # trains only the remaining rounds
                resume=os.environ.get("LGBMTPU_RESUME_MANIFEST"))
out = os.environ["LGBM_TPU_MODEL_OUT"]
bst.save_model(out + f".rank{wid}")
if wid == "0":
    meta = {"best_iteration": bst.best_iteration,
            "best_score": {d: dict(m) for d, m in bst.best_score.items()},
            "evals_result": {d: {k: list(map(float, v))
                                 for k, v in m.items()}
                             for d, m in evals_result.items()}}
    with open(out + ".meta.json", "w") as fh:
        json.dump(meta, fh)
if _snap_path:
    # stop the writer and flush one exact final snapshot — a clean exit's
    # fleet entry must not be a period stale
    _obs_metrics.stop_periodic_snapshots()
print("LAUNCHER_RANK_OK", wid, flush=True)
"""


# the most recent train_distributed launch directory — lets callers and
# tests locate fleet_events.jsonl / fleet_metrics.json after a FAILED
# launch too (the success path exposes them on the returned booster)
_LAST_LAUNCH_DIR: Optional[str] = None


class WorkerFailure(RuntimeError):
    """A launcher worker died (non-zero exit), HUNG (heartbeat went stale
    past the timeout), or the launch timed out.  Carries the failing rank
    (or None for timeouts) so retry logic and tests can tell the cases
    apart.  ``slice_id`` is set when the failure was handled
    slice-granularly (docs/ROBUSTNESS.md "Slice-granular recovery"):
    only that slice's process group was killed, the survivors are STILL
    RUNNING, and the caller owns respawning the slice."""

    def __init__(self, msg: str, rank: Optional[int] = None,
                 timed_out: bool = False, hung: bool = False,
                 slice_id: Optional[int] = None):
        super().__init__(msg)
        self.rank = rank
        self.timed_out = timed_out
        self.hung = hung
        self.slice_id = slice_id


def _kill_worker_group(proc: subprocess.Popen) -> None:
    """Kill a worker AND everything it spawned (each worker is started in
    its own session, so its process group is exactly its subtree) — no
    zombies may outlive a failed launch."""
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.kill()
        except OSError:
            pass
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        pass


def _log_tail(log_path: str, nbytes: int = 2000) -> str:
    try:
        with open(log_path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - nbytes))
            return fh.read().decode(errors="replace")
    except OSError as e:
        return f"<log unreadable: {e}>"


def _read_heartbeat(snap_path: Optional[str]) -> Optional[float]:
    """The ``heartbeat_ts`` gauge from a per-rank metrics snapshot file
    (the atomic JSON the worker's periodic writer keeps), or None while
    the rank has not started training / written a snapshot yet — or has
    RETIRED its heartbeat (``heartbeat_done``, set by engine.train's
    finally): the post-training tail (model save, final eval, fleet ack)
    may legitimately exceed the hang timeout and must not read as a
    stalled round loop."""
    if not snap_path:
        return None
    try:
        with open(snap_path, encoding="utf-8") as fh:
            snap = json.load(fh)
        gauges = snap.get("gauges", {})
        if gauges.get("heartbeat_done"):
            return None
        hb = gauges.get("heartbeat_ts")
        return float(hb) if hb is not None else None
    except (OSError, ValueError, AttributeError):
        return None  # missing/partial file: not a heartbeat signal yet


_SLOW_RANK_FLOOR_S = 1.0  # hard minimum for the slow-rank age floor; the
# effective floor adds headroom for the snapshot period + read cadence
# (see _watch_workers) so write/read phase aliasing can't false-positive


def _snapshot_period() -> float:
    """The workers' periodic metrics-snapshot period (the granularity at
    which heartbeat values can possibly change on disk)."""
    try:
        return float(os.environ.get(
            "LGBMTPU_METRICS_SNAPSHOT_PERIOD_S", "1.0"))
    except ValueError:
        return 1.0


def _watch_workers(workers, timeout_s: float,
                   poll_interval: float = 0.1,
                   heartbeat_timeout_s: Optional[float] = None,
                   heartbeat_paths: Optional[Dict[int, str]] = None,
                   slow_rank_factor: float = 0.0,
                   hb_ages: Optional[Dict[int, float]] = None,
                   slice_of: Optional[Dict[int, int]] = None,
                   slice_granular: bool = False,
                   done: Optional[set] = None) -> None:
    """Per-worker liveness watchdog: poll + exit-code harvest, plus
    HEARTBEAT staleness (docs/ROBUSTNESS.md "Elastic fleet recovery").

    ``workers`` is a list of (rank, Popen, log_path).  Returns when every
    worker exits 0.  A worker exiting non-zero fails the run within
    ~poll_interval seconds — not after a ``communicate(timeout=600)``
    hang waiting on the survivors, which block forever on the dead
    rank's collectives — with that worker's log tail in the error.

    With ``heartbeat_timeout_s`` > 0 and per-rank snapshot paths, a rank
    whose ``heartbeat_ts`` gauge stops CHANGING for longer than the
    timeout is declared HUNG (the wedged-in-a-collective class an
    exit-code watchdog can never catch: the process is alive, its
    snapshot-writer daemon keeps the file fresh, but the main thread
    stopped making rounds).  Change-tracking — not file mtime, not clock
    comparison — is deliberate on both counts: the daemon writer keeps
    mtime moving during a hang, and the gauge is the WORKER's monotonic
    clock, incomparable across processes.  Staleness is armed per rank
    from its first observed heartbeat; rendezvous hangs before round 1
    stay covered by ``timeout_s``.  The hung rank's process group is
    killed and the failure routes into the restart path exactly as a
    death does.

    ``slow_rank_factor`` > 0 adds straggler DETECTION on the same
    heartbeat reads (nothing is killed): a rank whose heartbeat age
    exceeds factor x the fleet median (and a 1 s floor) emits one
    ``fleet_slow_rank`` event + ``fleet_slow_ranks_total`` bump per slow
    episode — the class where a rank still makes rounds but k x slower
    than its peers, which the full-stall watchdog can never see.  With
    ``slice_of`` the median is computed WITHIN each rank's slice, not
    fleet-wide: slices make rounds at different cadences (DCN phase
    skew, per-slice data skew), so one slow SLICE would otherwise drag
    the fleet median up and mask a genuine straggler rank inside
    another slice.  ``hb_ages``, when given, is kept updated with each
    rank's current heartbeat age — the launcher's live /metrics
    collector reads it for the per-rank ``fleet_heartbeat_age_s``
    labeled gauge.

    On failure or timeout the WHOLE process group of every worker is
    killed and every tail is harvested (docs/ROBUSTNESS.md) — UNLESS
    ``slice_granular`` is set and the failure is attributable to one
    rank's slice: then only THAT slice's process groups are killed, the
    raised :class:`WorkerFailure` carries ``slice_id``, and the
    surviving slices keep running for the caller to rejoin a
    replacement slice against (docs/ROBUSTNESS.md "Slice-granular
    recovery")."""
    deadline = time.monotonic() + timeout_s
    # `done` may be threaded across calls (the slice-respawn loop
    # re-enters this watch): a rank that already exited 0 must not
    # re-emit its worker_exit event into the fleet flight recorder
    done = set() if done is None else done

    def _scoped_failure(rank, msg, hung=False):
        """Kill the blast radius and build the failure: the failing
        rank's slice alone under slice-granular handling (survivors keep
        running), the cleanup handler's whole-fleet kill otherwise."""
        sid = (slice_of.get(rank) if slice_granular and slice_of else None)
        if sid is not None:
            for r2, p2, _ in workers:
                if slice_of.get(r2) == sid and p2.poll() is None:
                    _kill_worker_group(p2)
        return WorkerFailure(msg, rank=rank, hung=hung, slice_id=sid)
    # rank -> (value, t_change, changed_once): staleness is armed only
    # after the heartbeat has been seen to CHANGE (see below)
    hb_seen: Dict[int, Tuple[float, float, bool]] = {}
    hb_next = 0.0
    slow_active: set = set()  # ranks currently in a slow episode
    watch_hb = bool((heartbeat_timeout_s or slow_rank_factor
                     or hb_ages is not None) and heartbeat_paths)
    try:
        while len(done) < len(workers):
            for rank, proc, log_path in workers:
                if rank in done:
                    continue
                rc = proc.poll()
                if rc is None:
                    continue
                if rc == 0:
                    done.add(rank)
                    _obs.event("worker_exit", worker_rank=rank, exit_code=0)
                    continue
                _obs.counter("launcher_worker_deaths_total").inc()
                _obs.event("worker_death", worker_rank=rank, exit_code=rc,
                           log=log_path)
                raise _scoped_failure(
                    rank,
                    f"launcher worker rank {rank} died with exit code {rc}; "
                    f"its failure scope killed. Tail of rank {rank}'s log "
                    f"({log_path}):\n{_log_tail(log_path)}")
            now = time.monotonic()
            if watch_hb and now >= hb_next:
                # re-read the small per-rank JSONs at most ~1 Hz (and at
                # least 4x per timeout window), not per 0.1 s poll tick
                hb_next = now + (min(1.0, heartbeat_timeout_s / 4.0)
                                 if heartbeat_timeout_s else 1.0)
                stalest: Optional[Tuple[float, int, "subprocess.Popen", str]] = None
                ages: Dict[int, float] = {}  # armed ranks' heartbeat age
                for rank, proc, log_path in workers:
                    if rank in done or proc.poll() is not None:
                        if hb_ages is not None:
                            hb_ages.pop(rank, None)
                        continue
                    hb = _read_heartbeat(heartbeat_paths.get(rank))
                    if hb is None:
                        if hb_ages is not None:
                            hb_ages.pop(rank, None)  # retired/not started
                        continue
                    prev = hb_seen.get(rank)
                    if prev is None:
                        # first observation arms tracking only: round 1
                        # includes jit COMPILATION, which stalls the
                        # heartbeat for arbitrarily long without being a
                        # hang — staleness counts only once the value has
                        # been seen to CHANGE (round 2 onward); earlier
                        # hangs stay covered by the launch timeout
                        hb_seen[rank] = (hb, now, False)
                        continue
                    if hb != prev[0]:
                        hb_seen[rank] = (hb, now, True)
                        ages[rank] = 0.0
                        continue
                    if not prev[2]:
                        continue
                    stale = now - prev[1]
                    ages[rank] = stale
                    if heartbeat_timeout_s and stale > heartbeat_timeout_s \
                            and (stalest is None or stale > stalest[0]):
                        # a wedged collective stalls EVERY rank's
                        # heartbeat; blame the stalest rank — it stopped
                        # first, the rest are its victims
                        stalest = (stale, rank, proc, log_path)
                if hb_ages is not None:
                    hb_ages.update(ages)
                if slow_rank_factor and len(ages) >= 2:
                    # straggler detection on the SAME reads: slow = this
                    # rank's heartbeat age is factor x the median of its
                    # COMPARISON GROUP (and past the absolute floor — an
                    # idle fleet's read-phase jitter must not trip it).
                    # The group is the rank's SLICE when slice_of is
                    # given — slices make rounds at different cadences,
                    # so a slow slice would inflate a fleet-wide median
                    # and mask a straggler inside a healthy slice —
                    # else the whole fleet.  Emitted once per episode;
                    # the rank clears when it catches up.  LOWER-middle
                    # median: the upper pick would let one straggler
                    # inflate its own threshold — in a 2-rank group a
                    # 60x-slow rank would BE the "median" and never
                    # trip.  Floor sized over the snapshot-write period
                    # + the 1 Hz read cadence: a healthy rank whose
                    # write phase lands just after our read shows age
                    # ~(period + read tick) without being slow.
                    groups: Dict[Optional[int], list] = {}
                    for rank, age in ages.items():
                        gid = slice_of.get(rank) if slice_of else None
                        groups.setdefault(gid, []).append(age)
                    med_of = {
                        gid: sorted(v)[(len(v) - 1) // 2]
                        for gid, v in groups.items()}
                    slow_floor = max(_SLOW_RANK_FLOOR_S,
                                     2.0 * _snapshot_period() + 1.0)
                    for rank, age in ages.items():
                        gid = slice_of.get(rank) if slice_of else None
                        if len(groups[gid]) < 2:
                            continue  # a lone rank has no peer cadence
                        med = med_of[gid]
                        slow = age > max(slow_rank_factor * med, slow_floor)
                        if slow and rank not in slow_active:
                            slow_active.add(rank)
                            _obs.counter("fleet_slow_ranks_total").inc()
                            _obs.event(
                                "fleet_slow_rank", worker_rank=rank,
                                age_s=round(age, 3),
                                fleet_median_s=round(med, 3),
                                factor=slow_rank_factor,
                                slice=gid)
                        elif not slow:
                            slow_active.discard(rank)
                if stalest is not None:
                    stale, rank, proc, log_path = stalest
                    _obs.counter("fleet_hangs_total").inc()
                    _obs.event("worker_hang", worker_rank=rank,
                               stale_s=round(stale, 3),
                               heartbeat_timeout_s=heartbeat_timeout_s,
                               log=log_path)
                    _kill_worker_group(proc)
                    raise _scoped_failure(
                        rank,
                        f"launcher worker rank {rank} HUNG: heartbeat "
                        f"unchanged for {stale:.1f}s "
                        f"(> {heartbeat_timeout_s:g}s); process group "
                        f"killed. Tail of rank {rank}'s log "
                        f"({log_path}):\n{_log_tail(log_path)}",
                        hung=True)
            if time.monotonic() > deadline:
                _obs.counter("launcher_timeouts_total").inc()
                _obs.event("launch_timeout", timeout_s=timeout_s)
                tails = "\n".join(
                    f"--- rank {r} ({lp}) ---\n{_log_tail(lp)}"
                    for r, _, lp in workers)
                raise WorkerFailure(
                    f"launcher timed out after {timeout_s:.0f}s; all worker "
                    f"process groups killed. Worker log tails:\n{tails}",
                    timed_out=True)
            time.sleep(poll_interval)
    except BaseException as e:
        # single cleanup path for death, timeout, and anything else:
        # no code path may leak live workers — EXCEPT a slice-scoped
        # failure, whose whole point is that the surviving slices stay
        # up for the replacement slice to rejoin (the slice's own
        # process groups were already killed at the raise site)
        if not (isinstance(e, WorkerFailure) and e.slice_id is not None):
            for _, p2, _ in workers:
                if p2.poll() is None:
                    _kill_worker_group(p2)
        raise


def _fleet_live_collector(tmp: str, num_machines: int,
                          hb_ages: Dict[int, float],
                          slice_of: Optional[Dict[int, int]] = None):
    """Snapshot-time collector serving the LIVE fleet view from the
    launcher's own /metrics endpoint (docs/OBSERVABILITY.md "Fleet
    metrics"): every per-rank periodic snapshot file is merged in with
    ``rank="r"`` labels — while the workers are still RUNNING, not only
    in the at-exit fleet_metrics.json merge — plus each rank's current
    heartbeat age (``fleet_heartbeat_age_s{rank="r"}``) as the watchdog
    tracks it.  Registered per launch (same collector name: the next
    launch replaces it); pure host-side file reads, zero device work,
    and a torn mid-write file just skips one scrape (the worker's writes
    are atomic)."""
    def collect() -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {"counters": {}, "gauges": {}}
        for r in range(num_machines):
            path = os.path.join(tmp, f"worker{r}.metrics.json")
            try:
                with open(path, encoding="utf-8") as fh:
                    snap = json.load(fh)
            except (OSError, ValueError):
                continue  # not written yet / torn: skip this scrape
            if not isinstance(snap, dict):
                continue
            for name, v in (snap.get("counters") or {}).items():
                try:
                    out["counters"][_obs.labeled(name, rank=r)] = int(v)
                except (TypeError, ValueError):
                    pass
            for name, v in (snap.get("gauges") or {}).items():
                try:
                    out["gauges"][_obs.labeled(name, rank=r)] = float(v)
                except (TypeError, ValueError):
                    pass
        for r, age in list(hb_ages.items()):
            labels = {"rank": r}
            if slice_of is not None and r in slice_of:
                # per-slice heartbeat labels (docs/OBSERVABILITY.md):
                # dashboards aggregate cadence per slice, the unit the
                # slow-rank detector medians over and recovery respawns
                labels["slice"] = slice_of[r]
            out["gauges"][_obs.labeled("fleet_heartbeat_age_s",
                                       **labels)] = float(age)
        return out

    return collect


def aggregate_fleet_events(tmp: str, num_machines: int,
                           since: float = 0.0) -> str:
    """Merge per-rank worker event JSONLs with the launcher's own
    lifecycle events (worker_spawn/worker_death/fleet_relaunch/
    launch_timeout, stamped rank=None) into ``<tmp>/fleet_events.jsonl``,
    sorted by timestamp.  ``since`` scopes the launcher's process-wide
    event ring to THIS run — a second train_distributed in the same
    process must not replay the previous fleet's deaths into its flight
    recorder.  Torn last lines from crashed workers are skipped, not
    fatal — the file is written on every exit path."""
    own = os.path.join(tmp, "launcher.events.jsonl")
    try:
        with open(own, "w", encoding="utf-8") as fh:
            for rec in _obs.events():
                if rec.get("ts", 0.0) >= since and str(
                        rec.get("kind", "")).startswith(
                        ("worker_", "fleet_", "launch_")):
                    fh.write(json.dumps(rec, default=str) + "\n")
    except OSError:
        own = None
    paths = [os.path.join(tmp, f"worker{r}.events.jsonl")
             for r in range(num_machines)]
    if own is not None:
        paths.append(own)
    out = os.path.join(tmp, "fleet_events.jsonl")
    _obs.merge_event_files(paths, out)
    return out


def aggregate_fleet_metrics(tmp: str, num_machines: int) -> str:
    """Merge per-rank metrics snapshot files (the periodic atomic writes
    each worker's obs layer keeps under ``<tmp>/worker<rank>.metrics.json``)
    into ``<tmp>/fleet_metrics.json`` — schema ``lgbmtpu-fleet-metrics-v1``,
    one entry per rank plus the aggregate (counters SUM, gauges MAX,
    latency reservoirs merged).  Missing rank files (a worker killed
    before its first write) are skipped, not fatal: this runs on success
    AND on every kill/crash exit path, and a partial fleet artifact still
    answers "which rank was behind / who died with what counters"."""
    paths = [os.path.join(tmp, f"worker{r}.metrics.json")
             for r in range(num_machines)]
    out = os.path.join(tmp, "fleet_metrics.json")
    _obs.merge_snapshot_files(paths, out)
    return out


def aggregate_fleet_trace(tmp: str, num_machines: int) -> Optional[str]:
    """Merge per-rank trace exports (each worker's engine writes its span
    ring to ``<tmp>/worker<rank>.trace.json`` via the LGBMTPU_TRACE_FILE
    env the launcher sets) into ``<tmp>/fleet_trace.json`` — one
    clock-aligned Chrome/Perfetto flight recorder, each rank in its own
    pid lane, trace ids and span links joining one request/rollover story
    across ranks.  Completes the events/metrics/trace merge triad.
    Missing rank files (a worker killed before its end-of-run write) are
    skipped, not fatal; returns None when NO rank left a trace."""
    paths = [p for p in (os.path.join(tmp, f"worker{r}.trace.json")
                         for r in range(num_machines))
             if os.path.exists(p)]
    if not paths:
        return None
    out = os.path.join(tmp, "fleet_trace.json")
    _trace.merge_trace_files(paths, out_path=out)
    return out


def _free_ports(k: int) -> list:
    """reference: dask.py _find_n_open_ports."""
    socks, ports = [], []
    for _ in range(k):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _shard_plan(n: int, num_machines: int,
                group: Optional[np.ndarray]) -> Tuple[List[Tuple[int, int]],
                                                      List, int]:
    """Row-shard plan: ((lo, hi) per rank, per-rank query sizes, padded
    per-rank size).  With `group`, shard boundaries snap to query
    boundaries (greedy contiguous fill, like the reference's dask module
    keeping partitions intact per worker)."""
    if group is not None:
        group = np.asarray(group, np.int64)
        if group.sum() != n:
            raise ValueError(
                f"group sizes sum to {group.sum()} but data has {n} rows")
        if len(group) < num_machines:
            raise ValueError(
                f"not enough queries ({len(group)}) for {num_machines} "
                "machines")
        bounds = np.concatenate([[0], np.cumsum(group)])
        shard_slices, shard_groups, q = [], [], 0
        for rank in range(num_machines):
            target = (n * (rank + 1)) // num_machines
            q0, q_cap = q, len(group) - (num_machines - rank - 1)
            q += 1  # at least one query per rank
            while q < q_cap and bounds[q + 1] <= target:
                q += 1
            if rank == num_machines - 1:
                q = len(group)
            shard_slices.append((int(bounds[q0]), int(bounds[q])))
            shard_groups.append(group[q0:q])
        per = max(hi - lo for lo, hi in shard_slices)
        return shard_slices, shard_groups, per
    per = -(-n // num_machines)
    shard_slices = [(r * per, min((r + 1) * per, n))
                    for r in range(num_machines)]
    return shard_slices, [None] * num_machines, per


def _rank_arrays(rank_slices, rank_groups, per, rank, X, y, weight):
    """One rank's (X, y, w, g) with weight-0 padding to the plan's `per`
    (equal shard sizes are a pre_partition requirement; padding rows carry
    weight 0 and, for ranking, one trailing pad query)."""
    lo, hi = rank_slices[rank]
    Xs, ys = X[lo:hi], np.asarray(y)[lo:hi]
    gs = rank_groups[rank]
    pad_s = per - (hi - lo)
    if weight is None and pad_s == 0:
        # no padding, no user weights: keep the unweighted fast paths
        return Xs, ys, np.asarray(()), gs
    ws = (np.asarray(weight, np.float64)[lo:hi]
          if weight is not None else np.ones(hi - lo, np.float64))
    if pad_s:
        Xs = np.concatenate([Xs, np.zeros((pad_s,) + Xs.shape[1:], Xs.dtype)])
        ys = np.concatenate([ys, np.zeros(pad_s, ys.dtype)])
        ws = np.concatenate([ws, np.zeros(pad_s)])
        if gs is not None:
            gs = np.concatenate([gs, [pad_s]])
    return Xs, ys, ws, gs


def train_distributed(
    params: Dict,
    X: np.ndarray,
    y: np.ndarray,
    num_boost_round: int = 100,
    *,
    num_machines: int = 2,
    weight: Optional[np.ndarray] = None,
    group: Optional[np.ndarray] = None,
    eval_set: Optional[Sequence[Tuple]] = None,  # [(Xe, ye), ...]
    eval_weight: Optional[Sequence] = None,
    eval_group: Optional[Sequence] = None,
    eval_names: Optional[Sequence[str]] = None,
    early_stopping_rounds: Optional[int] = None,
    devices_per_machine: int = 1,
    timeout_s: int = 600,
    env_extra: Optional[Dict[str, str]] = None,
    max_restarts: int = 0,
    restart_backoff_s: float = 1.0,
    heartbeat_timeout_s: Optional[float] = None,
    num_slices: Optional[int] = None,
    data_cache: Optional[str] = None,
):
    """Shard rows over `num_machines` local worker processes, train with
    tree_learner=data under pre_partition, and return (rank 0's Booster,
    per-rank model paths).  With eval_set, each eval set is row-sharded the
    same way; metrics sync across ranks (GlobalSyncUpBySum analogue) and
    early stopping fires identically on every rank.

    Worker liveness is supervised by :func:`_watch_workers`: a dead rank
    fails the launch in seconds with its log tail, a HUNG rank (heartbeat
    stale past ``heartbeat_timeout_s``, or the
    ``LGBMTPU_HEARTBEAT_TIMEOUT_S`` env / ``heartbeat_timeout_s`` param
    spelling) is killed and treated exactly like a death, and every
    failure path kills the full worker process groups (no zombies).

    ``max_restarts`` relaunches the whole fleet after a failure (fresh
    ports, re-written shards) with exponential backoff.  With
    ``snapshot_freq`` > 0 in ``params`` the fleet additionally keeps
    COORDINATED checkpoints (rank-0 snapshot + manifest + per-rank acks,
    utils/checkpoint.py), and a relaunch resumes every rank from the
    newest fleet-VALID round instead of round 0 — bitwise-identical to an
    uninterrupted run (docs/ROBUSTNESS.md "Elastic fleet recovery");
    without a valid manifest the relaunch falls back to a from-scratch
    restart, the round-8 behavior.

    ``num_slices`` > 1 (param or config) groups the ranks into slice
    worlds of num_machines/num_slices members each — the loopback
    control-plane form of multi-slice scale-out (docs/ROBUSTNESS.md
    "Slice-granular recovery"; the in-dispatch two-level DCN merge
    itself is parallel/hierarchy.py over a nested mesh).  Each slice is
    its own rendezvous world training the shared shard plan; the fleet
    manifests carry slice membership, the slow-rank detector compares
    heartbeats WITHIN a slice, and a rank failure kills + respawns ONLY
    its slice: the replacement resumes from the newest SLICE-valid
    manifest round (every surviving rank's ack present — the lost
    slice's own acks are not required) while the surviving slices never
    stop or restart."""
    import lightgbm_tpu as lgb

    cfg_launch = Config.from_dict(params)
    if num_slices is None:
        num_slices = max(int(cfg_launch.num_slices), 1)
    num_slices = max(int(num_slices), 1)
    ranks_per_slice = num_machines
    slice_of: Optional[Dict[int, int]] = None
    if num_slices > 1:
        if num_machines % num_slices:
            raise ValueError(
                f"num_machines={num_machines} does not divide into "
                f"num_slices={num_slices}")
        ranks_per_slice = num_machines // num_slices
        slice_of = {r: r // ranks_per_slice for r in range(num_machines)}

    if data_cache is not None:
        # rank-sharded cache feed (docs/DISTRIBUTED.md): rows come from
        # one shared save_binary cache; each worker streams ONLY its
        # shard via BinCacheStream(shard=) — the launcher never touches
        # the matrix, and ingest scales with the fleet
        from ..io.stream import BinCacheStream

        if X is not None or y is not None:
            raise ValueError("pass data_cache= XOR (X, y), not both")
        if weight is not None or group is not None or eval_set:
            raise ValueError(
                "data_cache= carries label/weight inside the cache; "
                "explicit weight/group/eval_set are not supported with "
                "the cache feed")
        n = BinCacheStream(data_cache).n_rows  # header read only
    else:
        n = X.shape[0]
    if group is not None:
        group = np.asarray(group, np.int64)
        if weight is None:
            weight = np.ones(n, np.float64)
    # in slice mode the shard plan covers ONE slice's ranks; every slice
    # trains the same plan (global rank r holds shard r % ranks_per_slice)
    shard_slices, shard_groups, per = _shard_plan(n, ranks_per_slice, group)

    for arg_name, arg in (("eval_names", eval_names),
                          ("eval_weight", eval_weight),
                          ("eval_group", eval_group)):
        if arg is not None and len(arg) != len(eval_set or ()):
            raise ValueError(
                f"{arg_name} has {len(arg)} entries but eval_set has "
                f"{len(eval_set or ())}")
    eval_plans = []
    for i, ev in enumerate(eval_set or ()):
        Xe, ye = ev[0], ev[1]
        ge = (np.asarray(eval_group[i], np.int64)
              if eval_group is not None and eval_group[i] is not None
              else None)
        we = (np.asarray(eval_weight[i], np.float64).ravel()
              if eval_weight is not None and eval_weight[i] is not None
              else None)
        ne = np.shape(Xe)[0]  # metadata only — no conversion (jaxlint R14)
        sl, gr, pe = _shard_plan(ne, ranks_per_slice, ge)
        name = (eval_names[i] if eval_names is not None
                else f"valid_{i}")
        eval_plans.append((np.asarray(Xe), np.asarray(ye).ravel(), we,
                           sl, gr, pe, name))

    global _LAST_LAUNCH_DIR
    tmp = _LAST_LAUNCH_DIR = tempfile.mkdtemp(prefix="lgbm_tpu_launch_")
    # fleet checkpoint cadence rides the standard snapshot params; the
    # launcher OWNS snapshotting for its workers (the per-round callback
    # in the worker body runs the manifest protocol), so the params the
    # workers' engine.train sees have snapshot_freq stripped — every rank
    # writing its own local snapshot family would race on shared paths
    # and vouch for nothing fleet-wide
    fleet_freq = max(int(cfg_launch.snapshot_freq), 0)
    fleet_keep = max(int(cfg_launch.snapshot_keep), 0)
    params = {k: v for k, v in dict(params).items()
              if _ALIASES.get(k, k) != "snapshot_freq"}
    if heartbeat_timeout_s is None:
        env_hb = os.environ.get("LGBMTPU_HEARTBEAT_TIMEOUT_S")
        heartbeat_timeout_s = (float(env_hb) if env_hb
                               else float(cfg_launch.heartbeat_timeout_s))
    env_slow = os.environ.get("LGBMTPU_SLOW_RANK_FACTOR")
    slow_rank_factor = (float(env_slow) if env_slow
                        else float(cfg_launch.slow_rank_factor))
    # live fleet observability (docs/OBSERVABILITY.md "Fleet metrics"):
    # the launcher's own /metrics endpoint serves the merged per-rank
    # snapshots + heartbeat ages WHILE workers run.  Opt-in via the same
    # metrics_port=/LGBMTPU_METRICS_PORT gate the trainers use; the
    # collector stays registered after the run (the snapshot files
    # persist), so a post-mortem scrape still sees the last fleet state.
    hb_ages: Dict[int, float] = {}
    _obs.register_collector(
        "fleet_live",
        _fleet_live_collector(tmp, num_machines, hb_ages, slice_of))
    from ..obs import server as _obs_server

    _obs_server.maybe_start(
        int(cfg_launch.metrics_port) if cfg_launch.is_set("metrics_port")
        else None)
    params_path = os.path.join(tmp, "params.npz")
    np.savez(params_path, params=np.asarray(dict(params), dtype=object))
    model_out = os.path.join(tmp, "model.txt")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # per-rank data-shard fingerprints: stamped into the fleet manifest by
    # rank 0 and checked by every resumed rank, so a resume can never
    # continue round k+1 on different data than rounds 1..k trained on.
    # Filled by the first _spawn_all (identical across relaunches — the
    # shard plan is deterministic) and published as one JSON file.
    shard_fps: Dict[str, str] = {}
    shards_json = os.path.join(tmp, "fleet_shards.json")
    # the newest fleet-valid manifest to resume from (set by the restart
    # path after a failure; None = fresh start)
    relaunch = {"resume_manifest": None}

    def _launch_once() -> None:
        # fresh ports per attempt: the previous fleet's listen sockets may
        # sit in TIME_WAIT, and the machines list is baked into the shards
        ports = _free_ports(num_machines)
        workers = []  # (rank, Popen, log_path)
        try:
            _write_shards(ports)
            for rank in range(num_machines):
                _spawn_rank(workers, rank, ports)
        except BaseException:
            # a failure while SPAWNING (disk full, fork failure on a later
            # rank) must not leak the ranks already started — the watchdog
            # cleanup only covers workers it was handed
            for _, p, _ in workers:
                if p.poll() is None:
                    _kill_worker_group(p)
            raise
        slice_restarts = 0
        done: set = set()  # threaded across re-watches (no re-emitted exits)
        while True:
            try:
                _watch_workers(
                    workers, timeout_s,
                    heartbeat_timeout_s=heartbeat_timeout_s or None,
                    heartbeat_paths={
                        r: os.path.join(tmp, f"worker{r}.metrics.json")
                        for r in range(num_machines)},
                    slow_rank_factor=slow_rank_factor,
                    hb_ages=hb_ages, slice_of=slice_of,
                    slice_granular=num_slices > 1, done=done)
                return
            except WorkerFailure as e:
                if e.slice_id is None or slice_restarts >= max_restarts:
                    # not slice-scoped, or the budget is spent: kill any
                    # survivors and hand the failure to the fleet-level
                    # restart path
                    for _, p, _ in workers:
                        if p.poll() is None:
                            _kill_worker_group(p)
                    raise
                slice_restarts += 1
                _respawn_slice(workers, e.slice_id, ports, slice_restarts,
                               done)

    def _respawn_slice(workers, sid: int, ports, attempt: int,
                       done: set) -> None:
        # slice-granular recovery (docs/ROBUSTNESS.md): ONLY the failed
        # slice restarts — from the newest SLICE-valid manifest round
        # (every surviving rank's ack present; the lost slice's own acks
        # cannot be required, its members are dead) — while the
        # surviving slices keep training untouched.  A slice member that
        # already EXITED 0 is not lost: its model file and acks are
        # complete, and respawning it would run an unwatched duplicate.
        lost = tuple(r for r in range(num_machines)
                     if slice_of[r] == sid and r not in done)
        resume_manifest = None
        resumed_round = None
        if fleet_freq > 0:
            fm = _checkpoint.latest_slice_valid_fleet_manifest(
                tmp, num_machines, lost)
            if fm is not None:
                resumed_round, resume_manifest, _ = fm
        _obs.counter("fleet_slice_resumes_total").inc()
        _obs.event("fleet_slice_resume", slice=sid, ranks=list(lost),
                   round=resumed_round, attempt=attempt)
        log_warning(
            f"slice {sid} (ranks {list(lost)}) failed; respawning it "
            + (f"from slice-valid manifest round {resumed_round}"
               if resumed_round is not None else "from scratch")
            + f" — surviving slices keep running (attempt {attempt})")
        excl = ",".join(str(r) for r in lost)
        for rank in lost:
            _spawn_rank(workers, rank, ports,
                        resume_manifest=resume_manifest,
                        exclude_ranks=excl)

    def _write_shards(ports) -> None:
        # phase 1 — write EVERY rank's shard file and publish the full
        # fingerprint table BEFORE any worker starts: rank 0 (spawned
        # first) reads fleet_shards.json once at startup, so writing it
        # while spawning the last rank would race — a manifest with no
        # fingerprints silently disables the changed-data resume guard.
        # In slice mode each slice is its own rendezvous world: global
        # rank r holds local shard r % ranks_per_slice and talks only to
        # its slice's machine list.
        for rank in range(num_machines):
            local = rank % ranks_per_slice
            sid = rank // ranks_per_slice
            slice_ports = ports[sid * ranks_per_slice:
                                (sid + 1) * ranks_per_slice]
            machines = ",".join(f"127.0.0.1:{p}" for p in slice_ports)
            shard_arrays = dict(
                num_machines=ranks_per_slice, machines=machines,
                local_listen_port=ports[rank], time_out=2,
                n_eval=len(eval_plans),
            )
            if data_cache is not None:
                # the cache feed ships NO arrays: the worker streams its
                # shard straight out of the shared cache, and the
                # fingerprint derives from the cache's CRC trailer table
                if str(rank) not in shard_fps:
                    from ..io.stream import cache_shard_fingerprint

                    lo, hi = shard_slices[local]
                    shard_fps[str(rank)] = cache_shard_fingerprint(
                        data_cache, lo, hi)
                np.savez(os.path.join(tmp, f"shard{rank}.npz"),
                         **shard_arrays)
                continue
            Xs, ys, ws, gs = _rank_arrays(shard_slices, shard_groups, per,
                                          local, X, y, weight)
            shard_arrays.update(
                X=Xs, y=ys, w=ws,
                g=(gs if gs is not None else np.asarray(())),
            )
            for i, (Xe, ye, we, sl, gr, pe, name) in enumerate(eval_plans):
                Xv, yv, wv, gv = _rank_arrays(sl, gr, pe, local, Xe, ye, we)
                shard_arrays[f"ev{i}_X"] = Xv
                shard_arrays[f"ev{i}_y"] = yv
                shard_arrays[f"ev{i}_w"] = wv
                shard_arrays[f"ev{i}_g"] = (gv if gv is not None
                                            else np.asarray(()))
                shard_arrays[f"ev{i}_name"] = name
            np.savez(os.path.join(tmp, f"shard{rank}.npz"), **shard_arrays)
            if str(rank) not in shard_fps:
                # fingerprint the shard DATA (not the npz bytes — zip
                # timestamps differ across relaunches): what round k+1
                # must see again for a resume to be sound
                h = hashlib.sha256()
                for arr in (Xs, ys, ws):
                    h.update(np.ascontiguousarray(arr).tobytes())
                if gs is not None:
                    h.update(np.ascontiguousarray(gs).tobytes())
                shard_fps[str(rank)] = h.hexdigest()
        if not os.path.exists(shards_json):
            with open(shards_json, "w", encoding="utf-8") as fh:
                json.dump(shard_fps, fh)

    def _spawn_rank(workers, rank: int, ports,
                    resume_manifest: Optional[str] = None,
                    exclude_ranks: str = "") -> None:
        shard_path = os.path.join(tmp, f"shard{rank}.npz")
        env = dict(os.environ)
        env.update(env_extra or {})
        # the rendezvous rank is slice-local; the worker id is global
        env["LIGHTGBM_TPU_RANK"] = str(rank % ranks_per_slice)
        env["LGBM_TPU_WORKER_ID"] = str(rank)
        env["LGBM_TPU_REPO"] = repo
        env["LGBM_TPU_SHARD"] = shard_path
        env["LGBM_TPU_PARAMS"] = params_path
        env["LGBM_TPU_ROUNDS"] = str(num_boost_round)
        env["LGBM_TPU_MODEL_OUT"] = model_out
        env["LGBM_TPU_ES_ROUNDS"] = str(early_stopping_rounds or 0)
        if data_cache is not None:
            lo, hi = shard_slices[rank % ranks_per_slice]
            env["LGBM_TPU_CACHE"] = os.fspath(data_cache)
            env["LGBM_TPU_CACHE_SHARD"] = f"{lo},{hi},{per}"
        env.pop("PYTEST_CURRENT_TEST", None)
        # per-rank structured event sink (docs/OBSERVABILITY.md): each
        # worker's obs layer appends rank-stamped JSONL records here;
        # the launcher merges them into one fleet-level file afterwards
        env["LGBMTPU_EVENTS_FILE"] = os.path.join(
            tmp, f"worker{rank}.events.jsonl")
        # per-rank metrics flight recorder: the worker body writes
        # atomic snapshots here periodically (and one exact final
        # write on clean exit); aggregate_fleet_metrics merges them
        # into fleet_metrics.json on every exit path — and the hang
        # watchdog reads each rank's heartbeat_ts gauge out of the
        # same file (no extra channel)
        env["LGBMTPU_METRICS_SNAPSHOT_FILE"] = os.path.join(
            tmp, f"worker{rank}.metrics.json")
        # per-rank trace export: the worker's engine writes its span ring
        # here at end of run (a params-level trace_file= still wins
        # inside the worker); aggregate_fleet_trace merges the rank
        # files into fleet_trace.json — the flight recorder's third
        # member.  Per-rank path always: inheriting one shared path from
        # the outer environment would have every rank clobber it.
        env["LGBMTPU_TRACE_FILE"] = os.path.join(
            tmp, f"worker{rank}.trace.json")
        # coordinated fleet checkpoints + resume-to-round relaunch
        # (docs/ROBUSTNESS.md "Elastic fleet recovery")
        if fleet_freq > 0:
            env["LGBMTPU_FLEET_CKPT_DIR"] = tmp
            env["LGBMTPU_FLEET_SNAPSHOT_FREQ"] = str(fleet_freq)
            env["LGBMTPU_FLEET_SNAPSHOT_KEEP"] = str(fleet_keep)
            env["LGBMTPU_FLEET_SHARDS_JSON"] = shards_json
        if num_slices > 1:
            env["LGBMTPU_FLEET_WORLD"] = str(num_machines)
            env["LGBMTPU_FLEET_SLICES"] = json.dumps(
                {str(r): s for r, s in slice_of.items()})
        env["LGBMTPU_SHARD_FINGERPRINT"] = shard_fps[str(rank)]
        if resume_manifest is None and relaunch["resume_manifest"]:
            resume_manifest = relaunch["resume_manifest"]
        if resume_manifest:
            env["LGBMTPU_RESUME_MANIFEST"] = resume_manifest
        if exclude_ranks:
            # slice respawn: the manifest is SLICE-valid (the lost
            # slice's acks are missing by definition); engine.train
            # validates with the lost ranks excluded
            env["LGBMTPU_RESUME_EXCLUDE_RANKS"] = exclude_ranks
        if env.get("LGBMTPU_FAULT"):
            # make injected faults once-only ACROSS restarts, so a
            # relaunched fleet runs clean (utils/faults.py)
            env.setdefault("LGBMTPU_FAULT_ONCE_DIR", tmp)
        # a RELAUNCH must not inherit the previous attempt's metrics
        # snapshot: the old file's static heartbeat_ts would read as a
        # live-but-stalled heartbeat while the new worker is still
        # importing, and the hang watchdog would kill it before its
        # first write
        try:
            os.unlink(env["LGBMTPU_METRICS_SNAPSHOT_FILE"])
        except OSError:
            pass
        # same for a previous attempt's trace export: a relaunched rank
        # must not leave a stale (pre-crash) span file to be merged as
        # if it were this attempt's history
        try:
            os.unlink(env["LGBMTPU_TRACE_FILE"])
        except OSError:
            pass
        # log file instead of a PIPE: a chatty worker cannot deadlock
        # on a full pipe buffer, and the watchdog can harvest tails
        # after the process is gone
        log_path = os.path.join(tmp, f"worker{rank}.log")
        with open(log_path, "wb") as log_fh:
            proc = subprocess.Popen(
                [sys.executable, "-c", _WORKER_SRC], env=env,
                stdout=log_fh, stderr=subprocess.STDOUT,
                start_new_session=True,  # own process group: killable
                # as a unit, no zombies past a timeout
            )
        # a respawned rank replaces its dead entry (the watch loop keys
        # liveness off this list)
        for i, (r, _p, _lp) in enumerate(workers):
            if r == rank:
                workers[i] = (rank, proc, log_path)
                break
        else:
            workers.append((rank, proc, log_path))
        _obs.counter("launcher_worker_spawns_total").inc()
        _obs.event("worker_spawn", worker_rank=rank, pid=proc.pid)

    attempt = 0
    run_started = time.time()  # scopes the event ring to this run's fleet
    try:
        while True:
            try:
                _launch_once()
                break
            except WorkerFailure as e:
                if attempt >= max_restarts:
                    raise
                delay = restart_backoff_s * (2 ** attempt)
                attempt += 1
                # resume-to-round (docs/ROBUSTNESS.md "Elastic fleet
                # recovery"): relaunch from the newest fleet-VALID
                # checkpoint round instead of round 0.  Only a manifest
                # that parses, whose snapshot verifies against its
                # ensemble sha, and that EVERY rank acked qualifies — a
                # crash mid-protocol (the manifest_write window) leaves
                # the previous round authoritative, and no manifest at
                # all falls back to the round-8 from-scratch restart.
                resumed_round = None
                if fleet_freq > 0:
                    fm = _checkpoint.latest_valid_fleet_manifest(
                        tmp, num_machines)
                    if fm is not None:
                        resumed_round, mpath, _ = fm
                        relaunch["resume_manifest"] = mpath
                        _obs.counter("fleet_resumes_total").inc()
                        _obs.gauge("fleet_resumed_round").set(resumed_round)
                        _obs.event("fleet_resume", round=resumed_round,
                                   manifest=mpath, attempt=attempt)
                _obs.counter("launcher_relaunches_total").inc()
                _obs.event("fleet_relaunch", attempt=attempt,
                           backoff_s=delay, cause=str(e)[:200],
                           hung=bool(getattr(e, "hung", False)),
                           resumed_round=resumed_round)
                log_warning(
                    f"launcher attempt {attempt}/{max_restarts + 1} failed "
                    f"({str(e)[:200]}); relaunching all workers in "
                    f"{delay:.1f}s"
                    + (f" from fleet checkpoint round {resumed_round}"
                       if resumed_round is not None else " from scratch"))
                time.sleep(delay)
    finally:
        # fleet-level observability artifact: merge every rank's JSONL
        # event stream (plus the launcher's own lifecycle events) into one
        # time-sorted file — written on success AND on failure, so a dead
        # fleet still leaves its flight recorder behind.  Best-effort: a
        # full disk here must not cost a trained model (nor mask the real
        # WorkerFailure on the failure path)
        try:
            fleet_events = aggregate_fleet_events(tmp, num_machines,
                                                  since=run_started)
        except OSError as e:
            log_warning(f"could not write fleet_events.jsonl: {e}")
            fleet_events = None
        # the metrics twin: merge whatever per-rank snapshot files exist
        # (periodic atomic writes survive kills) — success AND kill paths
        try:
            fleet_metrics = aggregate_fleet_metrics(tmp, num_machines)
        except OSError as e:
            log_warning(f"could not write fleet_metrics.json: {e}")
            fleet_metrics = None
        # the trace twin, completing the triad: merge whatever per-rank
        # trace exports exist into one clock-aligned flight recorder
        try:
            fleet_trace = aggregate_fleet_trace(tmp, num_machines)
        except (OSError, ValueError) as e:
            log_warning(f"could not write fleet_trace.json: {e}")
            fleet_trace = None
    booster = lgb.Booster(model_file=model_out + ".rank0")
    booster._fleet_events = fleet_events
    booster._fleet_metrics = fleet_metrics
    booster._fleet_trace = fleet_trace
    meta_path = model_out + ".meta.json"
    if os.path.exists(meta_path):
        with open(meta_path) as fh:
            meta = json.load(fh)
        booster.best_iteration = int(meta.get("best_iteration", -1))
        booster.best_score = meta.get("best_score", {})
        booster._distributed_evals_result = meta.get("evals_result", {})
    return booster, [
        model_out + f".rank{r}" for r in range(num_machines)
    ]
