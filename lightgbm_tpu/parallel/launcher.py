"""Multi-process training launcher — the Dask-orchestration analogue.

Reference: python-package/lightgbm/dask.py (~1,700 LoC): align partitions to
workers, find open ports, build the `machines` list, inject
num_machines/local_listen_port/tree_learner, run plain `lightgbm.train` on
every worker with network params, return the rank-0 model.

TPU-native redesign: workers are local processes wired through
`jax.distributed` (parallel/distributed.py maps the reference's machine-list
handshake onto the coordinator bring-up).  Each worker receives ONLY its row
shard (`pre_partition` semantics: bin boundaries sync from the global
sample, the global device array is assembled from process-local shards, and
no rank ever materializes the full dataset).  Every rank ends up with the
identical model; the launcher returns rank 0's.

This launcher is the single-host (loopback) form; on a real multi-host pod
run one worker per host with the same `machines` list — the worker body is
ordinary `lightgbm_tpu.train`, exactly like the reference's `_train_part`.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
from typing import Dict, Optional

import numpy as np

_WORKER_SRC = r"""
import os, sys
sys.path.insert(0, os.environ["LGBM_TPU_REPO"])
import numpy as np
from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.distributed import init_distributed

shard = np.load(os.environ["LGBM_TPU_SHARD"], allow_pickle=True)
net = {k: shard[k].item() for k in ("num_machines", "machines",
                                    "local_listen_port", "time_out")}
assert init_distributed(Config.from_dict(net))

import lightgbm_tpu as lgb

params = dict(np.load(os.environ["LGBM_TPU_PARAMS"], allow_pickle=True)[
    "params"].item())
params.update(net)
params["pre_partition"] = True
params.setdefault("tree_learner", "data")
ds = lgb.Dataset(
    shard["X"],
    label=shard["y"],
    weight=(shard["w"] if shard["w"].size > 0 else None),
    group=(shard["g"] if "g" in shard and shard["g"].size > 0 else None),
)
bst = lgb.train(params, ds, int(os.environ["LGBM_TPU_ROUNDS"]))
out = os.environ["LGBM_TPU_MODEL_OUT"]
bst.save_model(out + f".rank{os.environ['LIGHTGBM_TPU_RANK']}")
print("LAUNCHER_RANK_OK", os.environ["LIGHTGBM_TPU_RANK"], flush=True)
"""


def _free_ports(k: int) -> list:
    """reference: dask.py _find_n_open_ports."""
    socks, ports = [], []
    for _ in range(k):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def train_distributed(
    params: Dict,
    X: np.ndarray,
    y: np.ndarray,
    num_boost_round: int = 100,
    *,
    num_machines: int = 2,
    weight: Optional[np.ndarray] = None,
    group: Optional[np.ndarray] = None,
    devices_per_machine: int = 1,
    timeout_s: int = 600,
    env_extra: Optional[Dict[str, str]] = None,
):
    """Shard rows over `num_machines` local worker processes, train with
    tree_learner=data under pre_partition, and return rank 0's model as a
    Booster.  Rows are padded to equal shard sizes with weight-0 rows when
    the split is uneven (equal shards are a pre_partition requirement).

    With `group` (query sizes, ranking), shard boundaries snap to query
    boundaries (greedy contiguous fill, like the reference's dask module
    keeping partitions intact per worker) and each shard's padding rows
    form one trailing weight-0 query."""
    import lightgbm_tpu as lgb

    n = X.shape[0]
    if group is not None:
        group = np.asarray(group, np.int64)
        if group.sum() != n:
            raise ValueError(
                f"group sizes sum to {group.sum()} but X has {n} rows")
        if len(group) < num_machines:
            raise ValueError(
                f"not enough queries ({len(group)}) for {num_machines} "
                "machines")
        bounds = np.concatenate([[0], np.cumsum(group)])
        # greedy contiguous fill: each rank takes whole queries until its
        # proportional row share, always taking at least one and leaving
        # at least one per remaining rank
        shard_slices, shard_groups, q = [], [], 0
        for rank in range(num_machines):
            target = (n * (rank + 1)) // num_machines
            q0, q_cap = q, len(group) - (num_machines - rank - 1)
            q += 1  # at least one query per rank
            while q < q_cap and bounds[q + 1] <= target:
                q += 1
            if rank == num_machines - 1:
                q = len(group)
            shard_slices.append((int(bounds[q0]), int(bounds[q])))
            shard_groups.append(group[q0:q])
        per = max(hi - lo for lo, hi in shard_slices)
        if weight is None:
            weight = np.ones(n, np.float64)
    else:
        per = -(-n // num_machines)
        pad = per * num_machines - n
        if pad:
            X = np.concatenate([X, np.zeros((pad,) + X.shape[1:], X.dtype)])
            y = np.concatenate([y, np.zeros(pad, np.asarray(y).dtype)])
            weight = np.concatenate([
                np.ones(n) if weight is None
                else np.asarray(weight, np.float64),
                np.zeros(pad),
            ])
        shard_slices = [(r * per, (r + 1) * per) for r in range(num_machines)]
        shard_groups = [None] * num_machines
    ports = _free_ports(num_machines)
    machines = ",".join(f"127.0.0.1:{p}" for p in ports)

    tmp = tempfile.mkdtemp(prefix="lgbm_tpu_launch_")
    params_path = os.path.join(tmp, "params.npz")
    np.savez(params_path, params=np.asarray(dict(params), dtype=object))
    model_out = os.path.join(tmp, "model.txt")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    procs = []
    for rank in range(num_machines):
        lo, hi = shard_slices[rank]
        Xs, ys = X[lo:hi], np.asarray(y)[lo:hi]
        ws = (np.asarray(weight, np.float64)[lo:hi]
              if weight is not None else np.asarray(()))
        gs = shard_groups[rank]
        pad_s = per - (hi - lo)
        if pad_s:
            # equal shard sizes are a pre_partition requirement; pad rows
            # carry weight 0 (and, for ranking, one trailing pad query)
            Xs = np.concatenate([Xs, np.zeros((pad_s,) + Xs.shape[1:],
                                              Xs.dtype)])
            ys = np.concatenate([ys, np.zeros(pad_s, ys.dtype)])
            ws = np.concatenate([ws if ws.size else np.ones(hi - lo),
                                 np.zeros(pad_s)])
            if gs is not None:
                gs = np.concatenate([gs, [pad_s]])
        shard_path = os.path.join(tmp, f"shard{rank}.npz")
        np.savez(
            shard_path,
            X=Xs, y=ys, w=ws,
            g=(gs if gs is not None else np.asarray(())),
            num_machines=num_machines, machines=machines,
            local_listen_port=ports[rank], time_out=2,
        )
        env = dict(os.environ)
        env.update(env_extra or {})
        env["LIGHTGBM_TPU_RANK"] = str(rank)
        env["LGBM_TPU_REPO"] = repo
        env["LGBM_TPU_SHARD"] = shard_path
        env["LGBM_TPU_PARAMS"] = params_path
        env["LGBM_TPU_ROUNDS"] = str(num_boost_round)
        env["LGBM_TPU_MODEL_OUT"] = model_out
        env.pop("PYTEST_CURRENT_TEST", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER_SRC], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        ))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout_s)
        outs.append(out.decode())
    for rank, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"launcher worker rank {rank} failed:\n{out[-4000:]}")
    return lgb.Booster(model_file=model_out + ".rank0"), [
        model_out + f".rank{r}" for r in range(num_machines)
    ]
