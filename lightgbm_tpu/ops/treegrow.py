"""Jitted leaf-wise tree growth.

TPU-native re-design of the reference's serial tree learner
(reference: src/treelearner/serial_tree_learner.cpp ->
SerialTreeLearner::{Train,BeforeTrain,FindBestSplits,Split} and its CUDA
sibling src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp).

Design differences from the reference, chosen for XLA (SURVEY.md §10.1):
  * No per-leaf row-index lists (DataPartition).  Instead a per-row `leaf_id`
    vector is maintained; partitioning a leaf is a pure elementwise update and
    histogramming a leaf is a masked scatter.  Fixed shapes throughout.
  * The whole tree is grown inside ONE `lax.fori_loop` with `num_leaves - 1`
    trip count; exhausted trees turn remaining iterations into no-ops via
    `lax.cond` (the reference `break`s out of its leaf loop).
  * Histogram subtraction trick preserved: only the smaller child is
    histogrammed; the sibling is parent - child.
  * Under `shard_map` the same code runs data-parallel: histograms and leaf
    aggregates are `psum`'d over the mesh axis, after which every shard
    computes identical splits (reference analogue:
    src/treelearner/data_parallel_tree_learner.cpp, with psum standing in for
    ReduceScatter + SyncUpGlobalBestSplit).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from .histogram import histogram
from .split import (
    BestSplit, SplitParams, find_best_split, forced_split_candidate,
    gain_plane, leaf_output, leaf_output_smoothed, KMIN_SCORE,
)


class TreeArrays(NamedTuple):
    """Structure-of-arrays tree (reference: class Tree in include/LightGBM/tree.h).

    Internal node slots: 0..num_leaves-2 (slot t = t-th split).  Children
    encode leaves as ~leaf_index (negative), matching the reference's
    left_child_/right_child_ convention.
    """

    num_leaves: jnp.ndarray  # i32 scalar — actual leaf count
    split_feature: jnp.ndarray  # (L-1,) i32
    threshold_bin: jnp.ndarray  # (L-1,) i32
    default_left: jnp.ndarray  # (L-1,) bool
    split_gain: jnp.ndarray  # (L-1,) f32
    left_child: jnp.ndarray  # (L-1,) i32
    right_child: jnp.ndarray  # (L-1,) i32
    internal_value: jnp.ndarray  # (L-1,) f32 — leaf output the node would have
    internal_weight: jnp.ndarray  # (L-1,) f32 — sum hessian
    internal_count: jnp.ndarray  # (L-1,) f32
    leaf_value: jnp.ndarray  # (L,) f32
    leaf_weight: jnp.ndarray  # (L,) f32 — sum hessian
    leaf_count: jnp.ndarray  # (L,) f32
    leaf_sum_g: jnp.ndarray  # (L,) f32 (for quantized/renew paths)
    leaf_depth: jnp.ndarray  # (L,) i32
    is_cat: jnp.ndarray  # (L-1,) bool — node is a categorical (bitset) split
    cat_mask: jnp.ndarray  # (L-1, B) bool — bins going left at cat nodes
    path_features: Optional[jnp.ndarray] = None  # (L, F) bool (linear trees)


class GrowState(NamedTuple):
    leaf_id: jnp.ndarray  # (N,) i32
    hist: jnp.ndarray  # (L, 3, F, B) — channel-first (see ops/histogram.py)
    best: BestSplit  # vectorized over L
    leaf_sum_g: jnp.ndarray  # (L,)
    leaf_sum_h: jnp.ndarray
    leaf_count: jnp.ndarray
    leaf_depth: jnp.ndarray  # (L,) i32
    leaf_parent: jnp.ndarray  # (L,) i32 node the leaf hangs from (-1 for root)
    leaf_side: jnp.ndarray  # (L,) i32 0=left 1=right
    num_leaves_cur: jnp.ndarray  # i32
    leaf_out_lo: jnp.ndarray  # (L,) f32 — monotone output lower bounds
    leaf_out_hi: jnp.ndarray  # (L,) f32 — monotone output upper bounds
    leaf_out: jnp.ndarray  # (L,) f32 — each leaf's (smoothed/clipped) output
    cegb_used: jnp.ndarray  # (F,) bool — features already split on in this tree
    used_features: jnp.ndarray  # (L, F) bool or () — path features (interaction constraints)
    tree: TreeArrays
    forced_active: jnp.ndarray = True  # () bool — forced prefix still applying
    # (reference: ForceSplits stops at the FIRST invalid forced split; the
    # precomputed schedule's leaf ids assume every prior entry applied, so a
    # rejected entry must disable all later ones, not just itself)
    anc: jnp.ndarray = False  # (L, L-1) bool ancestor masks, or () placeholder
    aside: jnp.ndarray = False  # (L, L-1) bool — leaf on the RIGHT side of m
    # (maintained only for monotone_method="intermediate")
    node_mono: jnp.ndarray = False  # (L-1,) i32 monotone dir per node (0 at
    # cat nodes) — feature-parallel shards the constraint vector, so the
    # per-node direction must be recorded at split time (intermediate only)
    lazy_used: jnp.ndarray = False  # (N, F) bool — rows charged per feature
    lazy_counts: jnp.ndarray = False  # (L, F) f32 — per-leaf uncharged rows
    # (maintained only for CEGB cegb_penalty_feature_lazy; reference:
    # CostEfficientGradientBoosting feature_used_in_data bitset)


def _empty_best(num_leaves: int, num_bins: int) -> BestSplit:
    z = jnp.zeros((num_leaves,), dtype=jnp.float32)
    zi = jnp.zeros((num_leaves,), dtype=jnp.int32)
    return BestSplit(
        gain=jnp.full((num_leaves,), KMIN_SCORE, dtype=jnp.float32),
        feature=zi,
        threshold_bin=zi,
        default_left=jnp.zeros((num_leaves,), dtype=bool),
        is_cat=jnp.zeros((num_leaves,), dtype=bool),
        cat_mask=jnp.zeros((num_leaves, num_bins), dtype=bool),
        left_sum_g=z,
        left_sum_h=z,
        left_count=z,
        right_sum_g=z,
        right_sum_h=z,
        right_count=z,
    )


def _set_best(best: BestSplit, i: jnp.ndarray, s: BestSplit) -> BestSplit:
    return BestSplit(*[arr.at[i].set(v) for arr, v in zip(best, s)])


def _intermediate_bounds(anc, aside, node_mono, leaf_out, n_live, L):
    """Monotone 'intermediate' bounds (reference: monotone_constraints.hpp ->
    IntermediateLeafConstraints): instead of compounding midpoint fences
    (basic), each leaf is bounded by the ACTUAL output extremes of the
    opposite subtree at every monotone ancestor — sound under sequential
    splits because a new leaf respects all existing opposite-side leaves and
    future opposite-side leaves respect it in turn.

    anc/aside: (L, L-1) ancestor masks (aside = leaf on the right side).
    node_mono: (L-1,) per-node monotone direction, 0 at categorical nodes —
    recorded at split time because in feature-parallel mode the constraint
    vector is feature-SHARDED while tree.split_feature holds global ids
    (indexing it there would silently misindex).  Returns (lo, hi) (L,)."""
    live = (jnp.arange(L, dtype=jnp.int32) < n_live)[:, None]  # (L, 1)
    left_m = anc & ~aside & live  # (L, M) leaf ℓ lives in m's left subtree
    right_m = anc & aside & live
    o = leaf_out[:, None]
    ninf, pinf = -jnp.inf, jnp.inf
    l_max = jnp.max(jnp.where(left_m, o, ninf), axis=0)  # (M,)
    l_min = jnp.min(jnp.where(left_m, o, pinf), axis=0)
    r_max = jnp.max(jnp.where(right_m, o, ninf), axis=0)
    r_min = jnp.min(jnp.where(right_m, o, pinf), axis=0)
    d = node_mono  # (M,)
    # d=+1 (non-decreasing): right-side leaves >= max(left outputs),
    #                        left-side leaves <= min(right outputs)
    # d=-1 mirrored
    lo_c = jnp.maximum(
        jnp.where(right_m & (d > 0)[None, :], l_max[None, :], ninf),
        jnp.where(left_m & (d < 0)[None, :], r_max[None, :], ninf),
    )
    hi_c = jnp.minimum(
        jnp.where(left_m & (d > 0)[None, :], r_min[None, :], pinf),
        jnp.where(right_m & (d < 0)[None, :], l_min[None, :], pinf),
    )
    return jnp.max(lo_c, axis=1), jnp.min(hi_c, axis=1)


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_leaves",
        "num_bins",
        "max_depth",
        "params",
        "hist_strategy",
        "axis_name",
        "parallel_mode",
        "top_k",
        "track_path",
        "n_forced",
        "monotone_method",
    ),
)
def grow_tree(
    bins: jnp.ndarray,  # (N, F) int — binned features (device-resident)
    grad: jnp.ndarray,  # (N,) f32
    hess: jnp.ndarray,  # (N,) f32
    row_mask: jnp.ndarray,  # (N,) bool — bagging/GOSS row selection
    sample_weight: jnp.ndarray,  # (N,) f32 — GOSS amplification (1.0 if unused)
    feature_mask: jnp.ndarray,  # (F,) bool — feature_fraction selection
    num_bins_per_feature: jnp.ndarray,  # (F,) i32
    missing_bin_per_feature: jnp.ndarray,  # (F,) i32 (-1 = no missing bin)
    categorical_mask: jnp.ndarray = None,  # (F,) bool — categorical features
    monotone_constraints: jnp.ndarray = None,  # (F,) i32 in {-1,0,1}
    interaction_sets: jnp.ndarray = None,  # (S, F) bool — allowed feature sets
    rng_key: jnp.ndarray = None,  # base PRNG key (extra_trees / bynode)
    cegb_feature_penalty: jnp.ndarray = None,  # (F,) pre-scaled coupled penalties
    cegb_lazy_penalty: jnp.ndarray = None,  # (F,) pre-scaled lazy penalties
    cegb_lazy_used: jnp.ndarray = None,  # (N, F) bool — rows already charged
    forced_leaf: jnp.ndarray = None,  # (K,) i32 — forced-split schedule
    forced_feature: jnp.ndarray = None,  # (K,) i32   (reference: ForceSplits
    forced_bin: jnp.ndarray = None,  # (K,) i32        from forcedsplits JSON)
    feature_contri: jnp.ndarray = None,  # (F,) split-gain multipliers
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    hist_strategy: str = "auto",
    axis_name: Optional[str] = None,
    parallel_mode: str = "data",  # with axis_name: data | feature | voting
    top_k: int = 20,  # voting mode: per-shard feature votes (reference: top_k)
    track_path: bool = False,  # maintain per-leaf path features (linear trees)
    n_forced: int = 0,
    monotone_method: str = "basic",  # basic | intermediate (serial/data modes)
) -> tuple[TreeArrays, jnp.ndarray]:
    """Grow one tree; returns (tree, final leaf_id per row).

    `leaf_id` is maintained for ALL rows (in-bag and out-of-bag), so the score
    update after growth is simply `leaf_value[leaf_id]` — the partition-based
    fast path of the reference's ScoreUpdater::AddScore.
    """
    n, f = bins.shape
    bins = bins.astype(jnp.int32)
    grad = grad.astype(jnp.float32) * sample_weight
    hess = hess.astype(jnp.float32) * sample_weight
    L = num_leaves
    mode = parallel_mode if axis_name is not None else "serial"
    # CEGB lazy per-(row, feature) fetch charges (reference:
    # cost_effective_gradient_boosting.hpp — DeltaGain subtracts
    # penalty_feature_lazy[f] * #uncharged rows in the leaf; rows charge
    # when a split applies).  Serial-mode only: the (N, F) charge state is
    # row-global and the distributed wrappers do not thread it.
    use_lazy = (cegb_lazy_penalty is not None and cegb_lazy_used is not None
                and mode == "serial")
    use_intermediate = (
        monotone_method == "intermediate"
        and monotone_constraints is not None
        # serial: sequential splits, the textbook case.  data: every shard
        # holds identical replicated leaf state (hists are psummed before
        # split search).  feature/voting (round 5): the re-evaluate-all
        # path vmaps best_for over leaves, batching its collectives
        # (pmax/psum merges and the voting election) across the leaf dim —
        # every shard still computes identical bounds because leaf outputs
        # and node directions are replicated (node_mono records the split
        # feature's direction at split time, since the constraint vector
        # itself is feature-sharded in feature mode).
        and mode in ("serial", "data", "feature", "voting")
    )

    def psum(x):
        return jax.lax.psum(x, axis_name) if axis_name is not None else x

    def leaf_hist(mask):
        h = histogram(bins, grad, hess, mask, num_bins, strategy=hist_strategy)
        # data-parallel: rows sharded, merge now (reference ReduceScatter).
        # feature-parallel: each shard sees ALL rows for ITS features — local
        # hist is already complete.  voting: keep local, merge per-vote later.
        return psum(h) if mode == "data" else h

    def allowed_from_used(used):
        """Features allowed at a leaf = union of interaction sets containing
        ALL features already used on the leaf's path (reference:
        col_sampler.hpp interaction-constraint filtering)."""
        ok_s = ~jnp.any(used[None, :] & ~interaction_sets, axis=1)  # (S,)
        if mode == "feature":
            # a set qualifies only if no shard's local feature block used a
            # feature outside it (used/sets are column-sharded)
            ok_s = jax.lax.pmin(ok_s.astype(jnp.int32), axis_name) > 0
        return jnp.any(interaction_sets & ok_s[:, None], axis=0)  # (F,)

    def best_for(hist_leaf, sum_g, sum_h, count, depth, out_lo=None, out_hi=None,
                 used=None, node_id=None, parent_out=None, cegb_used=None,
                 lazy_counts=None):
        fmask = feature_mask
        if interaction_sets is not None and used is not None:
            fmask = fmask & allowed_from_used(used) if fmask is not None else allowed_from_used(used)
        key = None
        if rng_key is not None and node_id is not None:
            key = jax.random.fold_in(rng_key, node_id)
        cegb_pen = None
        if cegb_feature_penalty is not None:
            cegb_pen = jnp.where(cegb_used, 0.0, cegb_feature_penalty)
        if lazy_counts is not None:
            lz = cegb_lazy_penalty * lazy_counts
            cegb_pen = lz if cegb_pen is None else cegb_pen + lz
        kw = dict(
            feature_mask=fmask,
            categorical_mask=categorical_mask,
            monotone_constraints=monotone_constraints,
            out_lo=out_lo,
            out_hi=out_hi,
            rng_key=key,
            depth=depth.astype(jnp.float32) if hasattr(depth, 'astype') else jnp.float32(depth),
            parent_output=parent_out,
            cegb_feature_penalty=cegb_pen,
            feature_contri=feature_contri,
        )
        if mode == "voting":
            # PV-Tree (reference: voting_parallel_tree_learner.cpp): each
            # shard votes its top_k features by LOCAL gain; the global tally
            # elects ~2*top_k features whose histograms alone are merged.
            loc = jnp.sum(hist_leaf[:, 0, :], axis=1)  # local leaf totals (3,)
            local_gain, _ = gain_plane(
                hist_leaf, loc[0], loc[1], loc[2],
                num_bins_per_feature, missing_bin_per_feature, params, **kw,
            )
            per_f = jnp.max(local_gain, axis=1)  # (F,)
            kth = jax.lax.top_k(per_f, min(top_k, f))[0][-1]
            vote = (per_f >= kth) & (per_f > KMIN_SCORE / 2)
            tally = jax.lax.psum(vote.astype(jnp.int32), axis_name)
            # deterministic top-2k election, ties to the lower feature index
            score = tally.astype(jnp.int32) * (f + 1) - jnp.arange(f, dtype=jnp.int32)
            n_elect = min(2 * top_k, f)
            # DCN-frugal merge (the point of PV-Tree, reference:
            # VotingParallelTreeLearner: only elected features' histograms
            # cross the wire): gather the top-2k slice and psum THAT —
            # n_elect/F of the full-width bytes.  `score` is replicated
            # (built from the psum'd tally), so el_idx is identical on every
            # shard and the collective stays congruent.
            _, el_idx = jax.lax.top_k(score, n_elect)
            sub_hist = jax.lax.psum(hist_leaf[:, el_idx], axis_name)  # (3, E, B)

            def sub(arr):
                return None if arr is None else arr[el_idx]

            kw_sub = dict(kw)
            kw_sub["feature_mask"] = sub(kw["feature_mask"])
            kw_sub["categorical_mask"] = sub(kw_sub.get("categorical_mask"))
            kw_sub["monotone_constraints"] = sub(kw_sub.get("monotone_constraints"))
            if kw_sub.get("cegb_feature_penalty") is not None:
                kw_sub["cegb_feature_penalty"] = kw_sub["cegb_feature_penalty"][el_idx]
            if kw_sub.get("feature_contri") is not None:
                kw_sub["feature_contri"] = kw_sub["feature_contri"][el_idx]
            s = find_best_split(
                sub_hist, sum_g, sum_h, count,
                num_bins_per_feature[el_idx], missing_bin_per_feature[el_idx],
                params, **kw_sub,
            )
            s = s._replace(feature=el_idx[s.feature])
        else:
            s = find_best_split(
                hist_leaf, sum_g, sum_h, count,
                num_bins_per_feature, missing_bin_per_feature, params, **kw,
            )
        if mode == "feature":
            # feature-parallel merge (reference:
            # FeatureParallelTreeLearner::SyncUpGlobalBestSplit — Allreduce
            # with a max-gain reducer over serialized SplitInfo): winner rank
            # = lowest shard achieving the max gain; its SplitInfo (with the
            # feature index globalized) is broadcast by psum-masking.
            ax = jax.lax.axis_index(axis_name)
            nshards = jax.lax.psum(1, axis_name)
            gmax = jax.lax.pmax(s.gain, axis_name)
            cand = jnp.where(s.gain >= gmax, ax, nshards)
            wrank = jax.lax.pmin(cand, axis_name)
            sel = ax == wrank

            def bc(x):
                masked = jnp.where(sel, x, jnp.zeros_like(x))
                out = jax.lax.psum(masked.astype(jnp.float32) if x.dtype == bool else masked, axis_name)
                return (out > 0) if x.dtype == bool else out

            s = BestSplit(
                gain=gmax,
                feature=bc(s.feature + ax * f),
                threshold_bin=bc(s.threshold_bin),
                default_left=bc(s.default_left),
                is_cat=bc(s.is_cat),
                cat_mask=bc(s.cat_mask),
                left_sum_g=bc(s.left_sum_g),
                left_sum_h=bc(s.left_sum_h),
                left_count=bc(s.left_count),
                right_sum_g=bc(s.right_sum_g),
                right_sum_h=bc(s.right_sum_h),
                right_count=bc(s.right_count),
            )
        # depth cap (reference: max_depth check in BeforeFindBestSplit)
        if max_depth > 0:
            s = s._replace(gain=jnp.where(depth >= max_depth, KMIN_SCORE, s.gain))
        return s

    # --- leaf 0: all in-bag rows ---
    mask0 = row_mask.astype(jnp.float32)
    hist0 = leaf_hist(mask0)
    sum0 = jnp.sum(hist0[:, 0, :], axis=1)  # totals from feature 0's hist: (3,)
    if mode == "voting":
        sum0 = psum(sum0)  # local hists in voting mode; leaf stats are global
    g0, h0, c0 = sum0[0], sum0[1], sum0[2]

    leaf_out0 = leaf_output(g0, h0, params)
    cegb_used0 = jnp.zeros((f,), bool)
    if use_lazy:
        lazy_used0 = cegb_lazy_used
        lazy_counts0 = jnp.einsum(
            "n,nf->f", mask0, (~lazy_used0).astype(jnp.float32))

    tree0 = TreeArrays(
        num_leaves=jnp.asarray(1, jnp.int32),
        split_feature=jnp.zeros((L - 1,), jnp.int32),
        threshold_bin=jnp.zeros((L - 1,), jnp.int32),
        default_left=jnp.zeros((L - 1,), bool),
        split_gain=jnp.zeros((L - 1,), jnp.float32),
        left_child=jnp.zeros((L - 1,), jnp.int32),
        right_child=jnp.zeros((L - 1,), jnp.int32),
        internal_value=jnp.zeros((L - 1,), jnp.float32),
        internal_weight=jnp.zeros((L - 1,), jnp.float32),
        internal_count=jnp.zeros((L - 1,), jnp.float32),
        leaf_value=jnp.zeros((L,), jnp.float32),
        leaf_weight=jnp.zeros((L,), jnp.float32),
        leaf_count=jnp.zeros((L,), jnp.float32),
        leaf_sum_g=jnp.zeros((L,), jnp.float32),
        leaf_depth=jnp.zeros((L,), jnp.int32),
        is_cat=jnp.zeros((L - 1,), bool),
        cat_mask=jnp.zeros((L - 1, num_bins), bool),
    )

    state = GrowState(
        leaf_id=jnp.zeros((n,), jnp.int32),
        hist=jnp.zeros((L, 3, f, num_bins), jnp.float32).at[0].set(hist0),
        best=_set_best(
            _empty_best(L, num_bins), jnp.asarray(0),
            best_for(
                hist0, g0, h0, c0, jnp.asarray(0),
                out_lo=jnp.float32(-jnp.inf), out_hi=jnp.float32(jnp.inf),
                used=(jnp.zeros((f,), bool) if interaction_sets is not None else None),
                node_id=jnp.asarray(0, jnp.int32),
                parent_out=leaf_out0, cegb_used=cegb_used0,
                lazy_counts=(lazy_counts0 if use_lazy else None),
            ),
        ),
        leaf_sum_g=jnp.zeros((L,), jnp.float32).at[0].set(g0),
        leaf_sum_h=jnp.zeros((L,), jnp.float32).at[0].set(h0),
        leaf_count=jnp.zeros((L,), jnp.float32).at[0].set(c0),
        leaf_depth=jnp.zeros((L,), jnp.int32),
        leaf_parent=jnp.full((L,), -1, jnp.int32),
        leaf_side=jnp.zeros((L,), jnp.int32),
        num_leaves_cur=jnp.asarray(1, jnp.int32),
        leaf_out_lo=jnp.full((L,), -jnp.inf, jnp.float32),
        leaf_out_hi=jnp.full((L,), jnp.inf, jnp.float32),
        leaf_out=jnp.zeros((L,), jnp.float32).at[0].set(leaf_out0),
        cegb_used=cegb_used0,
        used_features=(
            jnp.zeros((L, f), bool)
            if (interaction_sets is not None or track_path)
            else jnp.zeros((), bool)
        ),
        tree=tree0,
        forced_active=jnp.asarray(True),
        anc=(jnp.zeros((L, L - 1), bool) if use_intermediate
             else jnp.zeros((), bool)),
        aside=(jnp.zeros((L, L - 1), bool) if use_intermediate
               else jnp.zeros((), bool)),
        node_mono=(jnp.zeros((L - 1,), jnp.int32) if use_intermediate
                   else jnp.zeros((), bool)),
        lazy_used=(lazy_used0 if use_lazy else jnp.zeros((), bool)),
        lazy_counts=(jnp.zeros((L, f), jnp.float32).at[0].set(lazy_counts0)
                     if use_lazy else jnp.zeros((), bool)),
    )

    def _forced_candidate(state: GrowState, i):
        """Materialize the i-th forced split (reference: ForceSplits —
        SerialTreeLearner applies the JSON tree prefix through the standard
        split evaluation, so constraints like min_data still gate it).
        Returns (leaf, BestSplit, valid)."""
        fi = jnp.minimum(i, n_forced - 1)
        fl = jnp.clip(forced_leaf[fi], 0, L - 1)
        s_f = forced_split_candidate(
            state.hist[fl], state.leaf_sum_g[fl], state.leaf_sum_h[fl],
            state.leaf_count[fl], num_bins_per_feature, missing_bin_per_feature,
            params, forced_feature[fi], forced_bin[fi],
            categorical_mask=categorical_mask,
            monotone_constraints=monotone_constraints,
            out_lo=state.leaf_out_lo[fl], out_hi=state.leaf_out_hi[fl],
            depth=state.leaf_depth[fl].astype(jnp.float32),
            parent_output=state.leaf_out[fl],
            feature_contri=feature_contri,
        )
        # valid = the forced leaf exists and the cell is a legal split
        valid = (forced_leaf[fi] < state.num_leaves_cur) & (s_f.gain > KMIN_SCORE / 2)
        if max_depth > 0:
            valid = valid & (state.leaf_depth[fl] < max_depth)
        return fl, s_f, valid

    def do_split(state: GrowState, forced=None) -> GrowState:
        best_leaf = jnp.argmax(state.best.gain).astype(jnp.int32)
        s = jax.tree.map(lambda a: a[best_leaf], state.best)
        if forced is not None:
            use_forced, f_leaf, s_f = forced
            best_leaf = jnp.where(use_forced, f_leaf, best_leaf)
            s = jax.tree.map(
                lambda a, b: jnp.where(use_forced, a, b), s_f, s
            )
        node = state.num_leaves_cur - 1  # next internal node slot
        new_leaf = state.num_leaves_cur  # right child's leaf index

        # --- partition: pure elementwise leaf_id update (reference:
        # DataPartition::Split, but with no data movement) ---
        if mode == "feature":
            # only the shard owning the winning feature can evaluate the
            # decision; rows are replicated, so broadcast go_left by psum
            # (reference: all machines apply the identical split after
            # SyncUpGlobalBestSplit because data is replicated)
            ax = jax.lax.axis_index(axis_name)
            local_f = s.feature - ax * f
            owned = (local_f >= 0) & (local_f < f)
            lf = jnp.clip(local_f, 0, f - 1)
            fcol = bins[:, lf]
            is_missing = fcol == missing_bin_per_feature[lf]
            gl_num = jnp.where(is_missing, s.default_left, fcol <= s.threshold_bin)
            gl = jnp.where(s.is_cat, s.cat_mask[fcol], gl_num) & owned
            go_left = jax.lax.psum(gl.astype(jnp.int32), axis_name) > 0
        else:
            fcol = bins[:, s.feature]
            is_missing = fcol == missing_bin_per_feature[s.feature]
            go_left_num = jnp.where(is_missing, s.default_left, fcol <= s.threshold_bin)
            # categorical: bin in the winning subset -> left (missing/unseen
            # bins never enter the subset: CategoricalDecision -> right)
            go_left = jnp.where(s.is_cat, s.cat_mask[fcol], go_left_num)
        in_leaf = state.leaf_id == best_leaf
        leaf_id = jnp.where(in_leaf & ~go_left, new_leaf, state.leaf_id)

        # --- histogram the smaller child; sibling by subtraction ---
        left_smaller = s.left_count <= s.right_count
        small_leaf = jnp.where(left_smaller, best_leaf, new_leaf)
        mask_small = (leaf_id == small_leaf) & row_mask
        hist_small = leaf_hist(mask_small.astype(jnp.float32))
        parent_hist = state.hist[best_leaf]
        hist_big = parent_hist - hist_small
        hist_left = jnp.where(left_smaller, hist_small, hist_big)
        hist_right = jnp.where(left_smaller, hist_big, hist_small)
        hist = state.hist.at[best_leaf].set(hist_left).at[new_leaf].set(hist_right)

        # --- record the node (reference: Tree::Split) ---
        parent_out = state.leaf_out[best_leaf]
        cegb_used = (
            state.cegb_used.at[s.feature].set(True)
            if cegb_feature_penalty is not None else state.cegb_used
        )
        if use_lazy:
            # charge the split leaf's in-bag rows for the split feature,
            # THEN compute the children's uncharged counts (a child split
            # on the same feature is free)
            charge = in_leaf & row_mask
            lazy_used = state.lazy_used.at[:, s.feature].set(
                state.lazy_used[:, s.feature] | charge)
            m_l = ((leaf_id == best_leaf) & row_mask).astype(jnp.float32)
            counts_l = jnp.einsum(
                "n,nf->f", m_l, (~lazy_used).astype(jnp.float32))
            # rows partition across leaves, so the parent's stored counts are
            # still current at split time; after charging s.feature the
            # children's counts for it are 0, and the right child holds the
            # remainder — one einsum instead of two
            parent_counts = state.lazy_counts[best_leaf].at[s.feature].set(0.0)
            counts_r = jnp.maximum(parent_counts - counts_l, 0.0)
            lazy_counts = (state.lazy_counts.at[best_leaf].set(counts_l)
                           .at[new_leaf].set(counts_r))
        else:
            lazy_used, lazy_counts = state.lazy_used, state.lazy_counts
        old_parent = state.leaf_parent[best_leaf]
        old_side = state.leaf_side[best_leaf]
        t = state.tree
        # re-point the grandparent's child slot from ~best_leaf to this node
        lc = jnp.where(
            (old_parent >= 0) & (old_side == 0),
            t.left_child.at[old_parent].set(node),
            t.left_child,
        )
        rc = jnp.where(
            (old_parent >= 0) & (old_side == 1),
            t.right_child.at[old_parent].set(node),
            t.right_child,
        )
        lc = lc.at[node].set(-best_leaf - 1)
        rc = rc.at[node].set(-new_leaf - 1)
        depth_child = state.leaf_depth[best_leaf] + 1
        tree = t._replace(
            num_leaves=state.num_leaves_cur + 1,
            split_feature=t.split_feature.at[node].set(s.feature),
            threshold_bin=t.threshold_bin.at[node].set(s.threshold_bin),
            default_left=t.default_left.at[node].set(s.default_left),
            split_gain=t.split_gain.at[node].set(s.gain),
            left_child=lc,
            right_child=rc,
            internal_value=t.internal_value.at[node].set(parent_out),
            internal_weight=t.internal_weight.at[node].set(state.leaf_sum_h[best_leaf]),
            internal_count=t.internal_count.at[node].set(state.leaf_count[best_leaf]),
            is_cat=t.is_cat.at[node].set(s.is_cat),
            cat_mask=t.cat_mask.at[node].set(s.cat_mask),
        )

        # --- update leaf aggregates ---
        leaf_sum_g = state.leaf_sum_g.at[best_leaf].set(s.left_sum_g).at[new_leaf].set(s.right_sum_g)
        leaf_sum_h = state.leaf_sum_h.at[best_leaf].set(s.left_sum_h).at[new_leaf].set(s.right_sum_h)
        leaf_count = state.leaf_count.at[best_leaf].set(s.left_count).at[new_leaf].set(s.right_count)
        leaf_depth = state.leaf_depth.at[best_leaf].set(depth_child).at[new_leaf].set(depth_child)
        leaf_parent = state.leaf_parent.at[best_leaf].set(node).at[new_leaf].set(node)
        leaf_side = state.leaf_side.at[best_leaf].set(0).at[new_leaf].set(1)

        # --- monotone bounds for the children (reference:
        # BasicLeafConstraints::SetChildrenConstraints — after a split on a
        # monotone feature the children's outputs are fenced at the midpoint
        # of the two clipped outputs; non-monotone splits inherit bounds) ---
        p_lo = state.leaf_out_lo[best_leaf]
        p_hi = state.leaf_out_hi[best_leaf]
        out_l_c = leaf_output_smoothed(s.left_sum_g, s.left_sum_h, s.left_count,
                                       parent_out, params)
        out_r_c = leaf_output_smoothed(s.right_sum_g, s.right_sum_h, s.right_count,
                                       parent_out, params)
        if monotone_constraints is not None:
            if mode == "feature":
                ax_m = jax.lax.axis_index(axis_name)
                lf_m = s.feature - ax_m * f
                owned_m = (lf_m >= 0) & (lf_m < f)
                mono_c = jax.lax.psum(
                    jnp.where(owned_m, monotone_constraints[jnp.clip(lf_m, 0, f - 1)], 0),
                    axis_name,
                )
            else:
                mono_c = monotone_constraints[s.feature]
            out_l = jnp.clip(out_l_c, p_lo, p_hi)
            out_r = jnp.clip(out_r_c, p_lo, p_hi)
            out_l_c, out_r_c = out_l, out_r
            mid = 0.5 * (out_l + out_r)
            l_hi = jnp.where(mono_c > 0, jnp.minimum(p_hi, mid), p_hi)
            r_lo = jnp.where(mono_c > 0, jnp.maximum(p_lo, mid), p_lo)
            l_lo = jnp.where(mono_c < 0, jnp.maximum(p_lo, mid), p_lo)
            r_hi = jnp.where(mono_c < 0, jnp.minimum(p_hi, mid), p_hi)
        else:
            l_lo, l_hi, r_lo, r_hi = p_lo, p_hi, p_lo, p_hi
        leaf_out_lo = state.leaf_out_lo.at[best_leaf].set(l_lo).at[new_leaf].set(r_lo)
        leaf_out_hi = state.leaf_out_hi.at[best_leaf].set(l_hi).at[new_leaf].set(r_hi)
        leaf_out = state.leaf_out.at[best_leaf].set(out_l_c).at[new_leaf].set(out_r_c)

        if use_intermediate:
            # maintain ancestor masks and recompute EVERY leaf's bounds from
            # the opposite-subtree output extremes (reference:
            # IntermediateLeafConstraints — looser than compounded midpoints)
            anc_child = state.anc[best_leaf].at[node].set(True)
            aside_l = state.aside[best_leaf]
            aside_r = aside_l.at[node].set(True)
            anc = state.anc.at[best_leaf].set(anc_child).at[new_leaf].set(anc_child)
            aside = state.aside.at[best_leaf].set(aside_l).at[new_leaf].set(aside_r)
            # record this node's monotone direction (mono_c was computed
            # above, psum-broadcast from the owner shard in feature mode)
            node_mono = state.node_mono.at[node].set(
                jnp.where(s.is_cat, 0, mono_c))
            leaf_out_lo, leaf_out_hi = _intermediate_bounds(
                anc, aside, node_mono, leaf_out,
                state.num_leaves_cur + 1, L,
            )
        else:
            anc, aside = state.anc, state.aside
            node_mono = state.node_mono

        if interaction_sets is not None or track_path:
            if mode == "feature":
                ax = jax.lax.axis_index(axis_name)
                local_f = s.feature - ax * f
                owned = (local_f >= 0) & (local_f < f)
                marked = state.used_features[best_leaf].at[
                    jnp.clip(local_f, 0, f - 1)
                ].set(True)
                used_child = jnp.where(owned, marked, state.used_features[best_leaf])
            else:
                used_child = state.used_features[best_leaf].at[s.feature].set(True)
            used_features = (
                state.used_features.at[best_leaf].set(used_child).at[new_leaf].set(used_child)
            )
            if interaction_sets is None:
                used_child = None  # path tracking only — not a split filter
        else:
            used_features = state.used_features
            used_child = None

        # --- best splits for the two fresh leaves ---
        if use_intermediate:
            # bounds of OTHER leaves may have moved (their opposite subtree
            # changed), so their cached best splits are stale — re-evaluate
            # every live leaf (reference: IntermediateLeafConstraints'
            # leaves_to_update recompute set; here the vectorized plane makes
            # recompute-all the simpler exact equivalent)
            node_ids_all = jnp.clip(leaf_parent, 0, None) * 2 + leaf_side + 1
            used_all = used_features if interaction_sets is not None else None

            def one(hist_l, g, h, c, dep, lo, hi, nid, pout, u, lzc):
                return best_for(hist_l, g, h, c, dep, out_lo=lo, out_hi=hi,
                                used=u, node_id=nid, parent_out=pout,
                                cegb_used=cegb_used, lazy_counts=lzc)

            in_axes = (0, 0, 0, 0, 0, 0, 0, 0, 0,
                       0 if used_all is not None else None,
                       0 if use_lazy else None)
            bb = jax.vmap(one, in_axes=in_axes)(
                hist, leaf_sum_g, leaf_sum_h, leaf_count, leaf_depth,
                leaf_out_lo, leaf_out_hi, node_ids_all, leaf_out, used_all,
                lazy_counts if use_lazy else None,
            )
            live_l = jnp.arange(L, dtype=jnp.int32) < (state.num_leaves_cur + 1)
            best = bb._replace(gain=jnp.where(live_l, bb.gain, KMIN_SCORE))
        else:
            bl = best_for(hist_left, s.left_sum_g, s.left_sum_h, s.left_count, depth_child,
                          out_lo=l_lo, out_hi=l_hi, used=used_child, node_id=2 * node + 1,
                          parent_out=out_l_c, cegb_used=cegb_used,
                          lazy_counts=(lazy_counts[best_leaf] if use_lazy else None))
            br = best_for(hist_right, s.right_sum_g, s.right_sum_h, s.right_count, depth_child,
                          out_lo=r_lo, out_hi=r_hi, used=used_child, node_id=2 * node + 2,
                          parent_out=out_r_c, cegb_used=cegb_used,
                          lazy_counts=(lazy_counts[new_leaf] if use_lazy else None))
            best = _set_best(_set_best(state.best, best_leaf, bl), new_leaf, br)

        return GrowState(
            leaf_id=leaf_id,
            hist=hist,
            best=best,
            leaf_sum_g=leaf_sum_g,
            leaf_sum_h=leaf_sum_h,
            leaf_count=leaf_count,
            leaf_depth=leaf_depth,
            leaf_parent=leaf_parent,
            leaf_side=leaf_side,
            num_leaves_cur=state.num_leaves_cur + 1,
            leaf_out_lo=leaf_out_lo,
            leaf_out_hi=leaf_out_hi,
            leaf_out=leaf_out,
            cegb_used=cegb_used,
            used_features=used_features,
            tree=tree,
            forced_active=state.forced_active,
            anc=anc,
            aside=aside,
            node_mono=node_mono,
            lazy_used=lazy_used,
            lazy_counts=lazy_counts,
        )

    def body(i, state: GrowState) -> GrowState:
        can_split = jnp.max(state.best.gain) > KMIN_SCORE / 2
        if n_forced > 0:
            f_leaf, s_f, f_valid = _forced_candidate(state, i)
            in_sched = i < n_forced
            use_forced = in_sched & f_valid & state.forced_active
            # first invalid in-schedule entry permanently disables the rest
            state = state._replace(
                forced_active=state.forced_active & (~in_sched | f_valid)
            )
            can_split = can_split | use_forced
            return jax.lax.cond(
                can_split,
                lambda st: do_split(st, forced=(use_forced, f_leaf, s_f)),
                lambda st: st,
                state,
            )
        return jax.lax.cond(can_split, do_split, lambda st: st, state)

    state = jax.lax.fori_loop(0, L - 1, body, state)

    # finalize leaf values (reference: leaf outputs are computed during growth;
    # equivalent here since sums are exact)
    if params.path_smooth > 0 or use_intermediate:
        # smoothed / monotone-clipped AT CREATION.  With intermediate bounds
        # this is required for correctness, not just convenience: bounds keep
        # evolving after a leaf is created, and re-clipping raw outputs to the
        # FINAL bounds can cross a monotone split (creation-time clips always
        # satisfy the pairwise invariant).
        leaf_value = state.leaf_out
    else:
        leaf_value = leaf_output(state.leaf_sum_g, state.leaf_sum_h, params)
        if monotone_constraints is not None:
            leaf_value = jnp.clip(leaf_value, state.leaf_out_lo, state.leaf_out_hi)
    active = jnp.arange(L, dtype=jnp.int32) < state.num_leaves_cur
    tree = state.tree._replace(
        num_leaves=state.num_leaves_cur,
        leaf_value=jnp.where(active, leaf_value, 0.0),
        leaf_weight=jnp.where(active, state.leaf_sum_h, 0.0),
        leaf_count=jnp.where(active, state.leaf_count, 0.0),
        leaf_sum_g=jnp.where(active, state.leaf_sum_g, 0.0),
        leaf_depth=state.leaf_depth,
        path_features=(state.used_features if track_path else None),
    )
    if use_lazy:
        # hand the cross-tree charge state back (reference: the
        # feature_used_in_data bitset persists across trees)
        return tree, state.leaf_id, state.lazy_used
    return tree, state.leaf_id
