"""The round megakernel: ONE HBM sweep of the bin matrix per boosting round.

The fused windowed round (ops/treegrow_windowed.py::_round_fused) is one
*dispatch* but — before this kernel — still three XLA/Pallas passes over
the window's bins inside it: the window gather reads W columns of the
(F, N) bin matrix, materializes a (W, F) copy in HBM, and the histogram
pass re-reads that copy; the Pallas partition streams the segment rows a
third time.  PERF_NOTES' roofline says histogram build is MEMORY-bound —
HBM traffic on the bin matrix, not FLOPs, bounds round time at any N —
so those are three full window-sweeps where one suffices (ROADMAP "round
megakernel"; docs/PERF_NOTES.md round 16).

This module fuses them into a single Pallas kernel with an HBM-resident
grid (``pltpu.ANY`` refs throughout — the jaxlint R11 discipline; nothing
row- or bin-proportional is ever staged whole in VMEM):

* **partition phase** — the round-12 ``make_async_copy`` chunk-DMA move
  sweep of ops/partition_pallas.py, minus the count sweep (the fused
  round already computed per-segment left counts for its window
  verification, so they arrive as scalar-prefetch operands) and with the
  round-12 queued follow-up applied: interior chunks skip the READ half
  of the read-modify-write destination pair (their fixed-size write tail
  lands inside the run and is overwritten by the next chunk's window;
  only boundary chunks can clobber a neighbour and keep the RMW).
  Partition movements are written to the output order on the way out.
* **histogram phase** — per feature block, the small-child windows of the
  freshly written order are streamed through double-buffered VMEM
  buffers: each window row's bin COLUMN is DMA'd from the HBM-resident
  matrix exactly once (copy-in row i+1 while accumulating row i) and
  folded into a per-leaf VMEM accumulator carry.  No (W, F) copy ever
  exists in HBM: the bin matrix is read once, in place.
* **split-gain phase** (single-device) — while a feature block's child
  histograms are still VMEM-resident, the candidate gain planes are
  evaluated and reduced PER FEATURE on-core via the shared machinery in
  ops/split.py (gain_plane + reduce_plane_per_feature — the same code
  the XLA path runs, so parity is structural); only the O(tile x F)
  per-feature bests leave the kernel, and the O(F) cross-feature argmax
  (select_from_feature_best) finishes outside.  Under SPMD the kernel
  stops after the histogram phase: the leaf-histogram merge must stay
  the round's single in-dispatch collective (psum / psum_scatter,
  UNCHANGED), so sibling subtraction and split search run post-merge in
  XLA exactly as before.

Bitwise contract: the kernel's histogram accumulator is the SCATTER
formulation — per window chunk, a seeded ``.at[].add`` fold continued on
the same accumulator, which preserves the per-bucket addition chain of
the XLA round's full-window scatter (the round-12 OOC rule: chunked
accumulation must seed-and-continue the SAME chain, never tree-reduce).
tests/test_megakernel.py pins the megakernel round bitwise-equal to the
three-pass round across the equivalence matrix (float / int8-quantized /
categorical, interpret mode on CPU).

Validation status (honest): this container has no TPU; the kernel is
validated through Mosaic INTERPRET mode, like partition_pallas v2 was.
The DMA constructs (per-chunk double buffering, per-row column gather —
the paged-attention-style pattern) follow the accelerator guide; the
scatter accumulate and the on-core gain reduction (argsort in the
categorical scan) are the two pieces Mosaic is expected to reject on
chip until the MXU one-hot accumulate variant lands (the hist_pallas
bf16x2 lanes, queued in docs/NEXT.md) — the utils/degrade.py registry
turns that into a logged permanent fallback to the three-pass round, not
a dead run.  Expected on-chip ceiling once landed: one bin-matrix sweep
per round (J7 pins ``<= 1`` statically) vs the three-pass round's three.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .hist_pallas import VMEM_ACC_BUDGET
from .partition_pallas import _CHUNK, emit_move_sweep
from .split import (FeatureBests, SplitParams, gain_plane,
                    reduce_plane_per_feature)


def megakernel_feature_block(num_bins: int, leaf_tile: int) -> int:
    """Feature-block width for the megakernel's VMEM carries, budgeted by
    the SAME constant the histogram kernels' leaf-tile policy uses
    (hist_pallas.VMEM_ACC_BUDGET — one policy, no duplicated numbers).
    Two (tile, 3, FB, B) f32 carries live at once (fresh accumulator +
    parent/staging block), so FB is sized for 2x."""
    bpad = max(num_bins, 8)
    per_f = 2 * leaf_tile * 3 * bpad * 4  # bytes per feature column
    fb = max(VMEM_ACC_BUDGET // max(per_f, 1), 8)
    return int(min(128, (fb // 8) * 8))


class _MKStatics(NamedTuple):
    """Trace-time geometry shared between the kernel body and the host
    wrapper (everything here is a Python int/bool at trace time)."""

    tile: int
    f: int
    num_bins: int
    fb: int  # feature-block width (megakernel_feature_block)
    fuse_tail: bool
    has_cat: bool
    has_contri: bool


def _mk_kernel(seg_start, seg_len, n_left, win_start, win_cnt, small_left,
               # ---- tensor operands (HBM unless noted) ----
               bins_hbm, order_hbm, go_hbm, pay_hbm, *rest,
               st: _MKStatics, params: SplitParams):
    """Single sequential grid step; phases ordered by data dependency
    (partition writes the order the histogram phase streams)."""
    T, F, B, FB = st.tile, st.f, st.num_bins, st.fb

    if st.fuse_tail:
        (parent_hbm, ptab, ftab_i, fcontri,
         out_order, left_out, right_out,
         fb_gain, fb_thr, fb_left, fb_var, fb_lg, fb_lh, fb_lc,
         obuf, gbuf, dbuf, wbuf, cbuf, pbuf, acc, pscr, sems) = rest
    else:
        (out_order, fresh_out,
         obuf, gbuf, dbuf, wbuf, cbuf, pbuf, acc, pscr, sems) = rest

    # ================= phase 1: segment partition (move sweep) =========
    # THE shared move sweep (partition_pallas.emit_move_sweep — one copy
    # of the cursor/boundary-RMW logic for both kernels), with the count
    # sweep replaced by the prefetched per-segment left counts.
    for s in range(T):
        emit_move_sweep(order_hbm, go_hbm, out_order, obuf, gbuf, dbuf,
                        sems, seg_start[s], seg_len[s], n_left[s])

    # ============ phase 2 (+3): window histograms, feature-block major ==
    # each window row's bin column is DMA'd from the HBM matrix ONCE;
    # the per-leaf accumulator is a VMEM carry across the whole window
    # sweep of one feature block.  Accumulation is the seeded scatter
    # fold (module docstring: bitwise contract with the XLA round).
    fb_blocks = [(lo, min(FB, F - lo)) for lo in range(0, F, FB)]
    for fb_lo, fbw in fb_blocks:
        acc[...] = jnp.zeros_like(acc)

        def bins_copy(row, i, fb_lo=fb_lo, fbw=fbw):
            return pltpu.make_async_copy(
                bins_hbm.at[pl.ds(fb_lo, fbw), pl.ds(row, 1)],
                cbuf.at[pl.ds(0, fbw), pl.ds(i, 1)], sems.at[jax.lax.rem(i, 2)])

        def pay_copy(row, i):
            return pltpu.make_async_copy(
                pay_hbm.at[:, pl.ds(row, 1)],
                pbuf.at[:, pl.ds(i, 1)], sems.at[2 + jax.lax.rem(i, 2)])

        for s in range(T):
            wst = win_start[s]
            wcnt = win_cnt[s]
            nc = pl.cdiv(wcnt, _CHUNK)

            def win_body(j, _, s=s, wst=wst, wcnt=wcnt, fb_lo=fb_lo,
                         fbw=fbw):
                # the window run is CONTIGUOUS in the partitioned order —
                # one chunk DMA; the fixed-size over-read past the window
                # tail is masked below (order_hbm-sized padding covers it)
                wc = pltpu.make_async_copy(
                    out_order.at[:, pl.ds(wst + j * _CHUNK, _CHUNK)],
                    wbuf, sems.at[4])
                wc.start()
                wc.wait()
                m = jnp.minimum(wcnt - j * _CHUNK, _CHUNK)
                pbuf[...] = jnp.zeros_like(pbuf)  # stale tails add exact 0

                # per-row column gather, double-buffered: start row i+1's
                # two DMAs while waiting on row i's (paged-attention
                # pattern: many small column DMAs, two in flight)
                @pl.when(m > 0)
                def _warm_row():
                    r0 = wbuf[0, 0]
                    bins_copy(r0, 0).start()
                    pay_copy(r0, 0).start()

                def row_body(i, _):
                    @pl.when(i + 1 < m)
                    def _prefetch():
                        rn = wbuf[0, i + 1]
                        bins_copy(rn, i + 1).start()
                        pay_copy(rn, i + 1).start()

                    ri = wbuf[0, i]
                    bins_copy(ri, i).wait()
                    pay_copy(ri, i).wait()
                    return 0

                jax.lax.fori_loop(0, m, row_body, 0)

                # seeded scatter fold of this chunk onto the carry —
                # identical per-bucket addition chain to the XLA round's
                # full-window scatter (histogram_scatter), restricted to
                # this slot's rows (zero-payload adds are exact no-ops)
                binv = jnp.clip(
                    cbuf[:, :].astype(jnp.int32).T[:, :fbw], 0, B - 1)
                g, h, mk = pbuf[0], pbuf[1], pbuf[2]
                payload = jnp.stack([g * mk, h * mk, mk])  # (3, _CHUNK)
                idx = binv + (jnp.arange(fbw, dtype=jnp.int32) * B)[None, :]
                a3 = acc[s].reshape(3, FB * B)[:, : fbw * B]
                a3 = a3.at[:, idx].add(payload[:, :, None])
                acc[s, :, : fbw, :] = a3.reshape(3, fbw, B)
                return 0

            jax.lax.fori_loop(0, nc, win_body, 0)

        if not st.fuse_tail:
            wr = pltpu.make_async_copy(
                acc.at[:, :, pl.ds(0, fbw), :],
                fresh_out.at[:, :, pl.ds(fb_lo, fbw), :], sems.at[5])
            wr.start()
            wr.wait()
            continue

        # ---- phase 3: sibling subtraction + on-core gain reduction ----
        # parent slot histograms for THIS feature block come in by DMA,
        # children are written back out, and the split-gain planes are
        # evaluated + reduced per feature while everything is VMEM-
        # resident (ops/split.py shared machinery; module docstring)
        prd = pltpu.make_async_copy(
            parent_hbm.at[:, :, pl.ds(fb_lo, fbw), :],
            pscr.at[:, :, pl.ds(0, fbw), :], sems.at[5])
        prd.start()
        prd.wait()
        fresh = acc[:, :, :fbw, :]
        parent = pscr[:, :, :fbw, :]
        big = parent - fresh
        sml = (small_left_vec(small_left, T) > 0)[:, None, None, None]
        left_h = jnp.where(sml, fresh, big)
        right_h = jnp.where(sml, big, fresh)
        acc[:, :, : fbw, :] = left_h
        wr = pltpu.make_async_copy(
            acc.at[:, :, pl.ds(0, fbw), :],
            left_out.at[:, :, pl.ds(fb_lo, fbw), :], sems.at[5])
        wr.start()
        wr.wait()
        acc[:, :, : fbw, :] = right_h
        wr = pltpu.make_async_copy(
            acc.at[:, :, pl.ds(0, fbw), :],
            right_out.at[:, :, pl.ds(fb_lo, fbw), :], sems.at[5])
        wr.start()
        wr.wait()

        cand = jnp.concatenate([left_h, right_h], axis=0)  # (2T, 3, fbw, B)
        nbpf_fb = ftab_i[0, fb_lo:fb_lo + fbw]
        mbpf_fb = ftab_i[1, fb_lo:fb_lo + fbw]
        fmask_fb = ftab_i[2, fb_lo:fb_lo + fbw] > 0
        cmask_fb = (ftab_i[3, fb_lo:fb_lo + fbw] > 0) if st.has_cat else None
        fc_fb = fcontri[0, fb_lo:fb_lo + fbw] if st.has_contri else None

        def cand_bests(hist_c, pg, ph, pc, dep, pout):
            gain, ctx = gain_plane(
                hist_c, pg, ph, pc, nbpf_fb, mbpf_fb, params,
                feature_mask=fmask_fb, categorical_mask=cmask_fb,
                depth=dep, parent_output=pout, feature_contri=fc_fb)
            return reduce_plane_per_feature(gain, ctx)

        out = jax.vmap(cand_bests)(
            cand, ptab[0], ptab[1], ptab[2], ptab[3], ptab[4])
        fb_gain[:, fb_lo:fb_lo + fbw] = out.gain
        fb_thr[:, fb_lo:fb_lo + fbw] = out.threshold_bin
        fb_left[:, fb_lo:fb_lo + fbw] = out.use_left.astype(jnp.int32)
        fb_var[:, fb_lo:fb_lo + fbw] = out.variant
        fb_lg[:, fb_lo:fb_lo + fbw] = out.left_g
        fb_lh[:, fb_lo:fb_lo + fbw] = out.left_h
        fb_lc[:, fb_lo:fb_lo + fbw] = out.left_c


def small_left_vec(small_left, tile: int):
    """Scalar-prefetch operands are SMEM scalars; rebuild the (T,) vector
    the tail's broadcast select needs."""
    return jnp.asarray([small_left[i] for i in range(tile)], jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "leaf_tile", "params", "fuse_tail",
                     "has_cat", "interpret"),
)
def round_megakernel(
    bins_t: jnp.ndarray,  # (F, N) int16 — HBM-resident, read ONCE
    order: jnp.ndarray,  # (N,) i32 — pre-round physical row order
    go_left: jnp.ndarray,  # (N,) bool per POSITION
    grad: jnp.ndarray,  # (N,) f32 by ROW id (dequantized under quant)
    hess: jnp.ndarray,
    row_mask: jnp.ndarray,  # (N,) bool by ROW id
    seg_start: jnp.ndarray,  # (T,) i32 split-segment geometry
    seg_len: jnp.ndarray,
    n_left: jnp.ndarray,  # (T,) i32 — per-segment left counts (precomputed)
    win_start: jnp.ndarray,  # (T,) i32 — small-child window geometry
    win_cnt: jnp.ndarray,
    small_left: jnp.ndarray,  # (T,) i32 — 1 when the left child is windowed
    parent_hists: Optional[jnp.ndarray] = None,  # (T, 3, F, B) fuse_tail
    cand_tab: Optional[jnp.ndarray] = None,  # (5, 2T) f32 fuse_tail
    num_bins_pf: Optional[jnp.ndarray] = None,
    missing_bin_pf: Optional[jnp.ndarray] = None,
    feature_mask: Optional[jnp.ndarray] = None,
    categorical_mask: Optional[jnp.ndarray] = None,
    feature_contri: Optional[jnp.ndarray] = None,
    *,
    num_bins: int,
    leaf_tile: int,
    params: SplitParams = SplitParams(),
    fuse_tail: bool = False,
    has_cat: bool = False,
    interpret: bool = False,
):
    """One round's partition + window histograms (+ on-core split-gain
    reduction when ``fuse_tail``) in a single Pallas call.

    Returns ``(raw_order, fresh_hists)`` without the tail (the caller
    merges raw_order over untouched positions and runs merge/subtraction/
    search as before — the sharded path), or ``(raw_order, left_hists,
    right_hists, FeatureBests)`` with it (the caller finishes with
    select_from_feature_best).  ``raw_order`` is defined INSIDE segments
    only, same contract as partition_pallas."""
    f, n = bins_t.shape
    T = leaf_tile
    FB = min(megakernel_feature_block(num_bins, leaf_tile), f)
    B = num_bins
    n_pad = (pl.cdiv(n, _CHUNK) + 1) * _CHUNK
    order_p = jnp.pad(order, (0, n_pad - n))[None]
    go_p = jnp.pad(go_left.astype(jnp.int32), (0, n_pad - n))[None]
    pay = jnp.stack([grad.astype(jnp.float32), hess.astype(jnp.float32),
                     row_mask.astype(jnp.float32)])  # (3, N)
    st = _MKStatics(tile=T, f=f, num_bins=B, fb=FB, fuse_tail=fuse_tail,
                    has_cat=has_cat, has_contri=feature_contri is not None)

    tensor_in = [bins_t, order_p, go_p, pay]
    in_specs = [pl.BlockSpec(memory_space=pltpu.ANY)] * 4
    out_shape = [jax.ShapeDtypeStruct((1, n_pad), jnp.int32)]
    out_specs = [pl.BlockSpec(memory_space=pltpu.ANY)]
    if fuse_tail:
        ftab_i = jnp.stack([
            jnp.asarray(num_bins_pf, jnp.int32),
            jnp.asarray(missing_bin_pf, jnp.int32),
            jnp.asarray(feature_mask, jnp.int32),
            (jnp.asarray(categorical_mask, jnp.int32) if has_cat
             else jnp.zeros((f,), jnp.int32)),
        ])  # (4, F)
        fc = (jnp.asarray(feature_contri, jnp.float32)[None]
              if feature_contri is not None
              else jnp.zeros((1, f), jnp.float32))
        tensor_in += [parent_hists, cand_tab, ftab_i, fc]
        in_specs += [
            pl.BlockSpec(memory_space=pltpu.ANY),  # parent hists: HBM, DMA
            # jaxlint: disable=R11 (O(tile) candidate scalars — a few hundred bytes, not row-proportional)
            pl.BlockSpec((5, 2 * T), lambda i, *_: (0, 0),
                         memory_space=pltpu.VMEM),
            # jaxlint: disable=R11 (O(F) per-feature int tables for the on-core gain scan — KBs, not row-proportional)
            pl.BlockSpec((4, f), lambda i, *_: (0, 0),
                         memory_space=pltpu.VMEM),
            # jaxlint: disable=R11 (O(F) feature_contri row — same table class as above)
            pl.BlockSpec((1, f), lambda i, *_: (0, 0),
                         memory_space=pltpu.VMEM),
        ]
        out_shape += [
            jax.ShapeDtypeStruct((T, 3, f, B), jnp.float32),  # left hists
            jax.ShapeDtypeStruct((T, 3, f, B), jnp.float32),  # right hists
            jax.ShapeDtypeStruct((2 * T, f), jnp.float32),  # per-F gain
            jax.ShapeDtypeStruct((2 * T, f), jnp.int32),  # threshold
            jax.ShapeDtypeStruct((2 * T, f), jnp.int32),  # use_left
            jax.ShapeDtypeStruct((2 * T, f), jnp.int32),  # variant
            jax.ShapeDtypeStruct((2 * T, f), jnp.float32),  # left_g
            jax.ShapeDtypeStruct((2 * T, f), jnp.float32),  # left_h
            jax.ShapeDtypeStruct((2 * T, f), jnp.float32),  # left_c
        ]
        out_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2 + [
            # jaxlint: disable=R11 (O(tile x F) REDUCED per-feature bests — the point of the on-core reduction; not row- or bin-proportional)
            pl.BlockSpec((2 * T, f), lambda i, *_: (0, 0),
                         memory_space=pltpu.VMEM)] * 7
    else:
        out_shape += [jax.ShapeDtypeStruct((T, 3, f, B), jnp.float32)]
        out_specs += [pl.BlockSpec(memory_space=pltpu.ANY)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(1,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((2, 1, _CHUNK), jnp.int32),  # order chunks (dbl-buf)
            pltpu.VMEM((2, 1, _CHUNK), jnp.int32),  # go chunks (dbl-buf)
            pltpu.VMEM((2, 1, _CHUNK), jnp.int32),  # left/right RMW windows
            pltpu.VMEM((1, _CHUNK), jnp.int32),  # window order values
            pltpu.VMEM((FB, _CHUNK), bins_t.dtype),  # gathered bin columns
            pltpu.VMEM((3, _CHUNK), jnp.float32),  # gathered payload columns
            # the two (tile, 3, FB, B) carries are the budgeted exception:
            # FB is sized from VMEM_ACC_BUDGET so together they stay under
            # the shared accumulator headroom, independent of N
            pltpu.VMEM((T, 3, FB, B), jnp.float32),  # fresh-hist carry
            pltpu.VMEM((T, 3, FB, B), jnp.float32),  # parent/staging block
            pltpu.SemaphoreType.DMA((6,)),
        ],
    )
    outs = pl.pallas_call(
        functools.partial(_mk_kernel, st=st, params=params),
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(seg_start.astype(jnp.int32), seg_len.astype(jnp.int32),
      n_left.astype(jnp.int32), win_start.astype(jnp.int32),
      win_cnt.astype(jnp.int32), small_left.astype(jnp.int32),
      *tensor_in)

    raw_order = outs[0][0, :n]
    if not fuse_tail:
        return raw_order, outs[1]
    left_hists, right_hists = outs[1], outs[2]
    bests = FeatureBests(
        gain=outs[3], threshold_bin=outs[4], use_left=outs[5] > 0,
        variant=outs[6], left_g=outs[7], left_h=outs[8], left_c=outs[9])
    return raw_order, left_hists, right_hists, bests
