"""Pallas TPU kernel for the leaf-ordered row partition — v2, HBM-resident.

The XLA implementation (ops/partition.py::stable_partition_ranges) is
exact but pays O(N) regardless of how few rows a round actually splits:
two full-N cumsums plus a full-N permutation scatter measured ~41 ms per
1M-row round on a v5e — pure fixed cost from the windowed grower's admit
phase (docs/NEXT.md round-6 lever 1).  A round only *moves* the rows
inside its split segments (the parents of this round's splits, at most
2x the round's window), so the data movement should be window-
proportional, like the reference's in-place ``DataPartition::Split``
(src/treelearner/data_partition.hpp) which touches only the split leaf's
``[start, count)`` index range.

v1 (rounds 7-11) was that in-place split but staged ``order``/``go``/
``out`` as whole-array VMEM blocks (~12 B/row across the three buffers):
compute was segment-proportional, STAGING was O(N), and the scoped-VMEM
budget capped the kernel at ``_MAX_VMEM_ROWS = 650_000`` rows with a
silent XLA fallback above — exactly the regime the Higgs-11M target
lives in (ROADMAP "Uncap N").  v2 removes the cap:

* ``order``/``go_left``/``out`` live in HBM (``pltpu.ANY`` refs — no
  BlockSpec staging at all); the kernel streams fixed-size chunks
  through a small double-buffered VMEM scratch via
  ``pltpu.make_async_copy`` DMA, starting chunk c+1's copy-in while
  chunk c is being placed.  VMEM residency is O(_CHUNK), independent
  of N — the jaxlint R11 ``whole-array-vmem-staging`` fix pattern.
* grid ``(S,)`` — one sequential grid step per segment.  Per segment:
  a COUNT sweep (vector masked sums of streamed ``go`` chunks ->
  ``n_left``), then a MOVE sweep placing each input chunk's rows into
  the segment's left run ``[start, start+n_left)`` and right run
  ``[start+n_left, start+len)``.
* the move sweep compacts each chunk's left/right rows into VMEM
  staging buffers (scalar stores — the same SREG-bound ceiling as v1's
  move loop) and writes each run back with a read-modify-write DMA
  pair: the destination window is copied in, overlaid from its cursor,
  and copied back, so the fixed-size DMA's tail can never clobber
  neighbouring data (runs are cursor-contiguous; RMW makes the
  overhang idempotent).  Round 16: INTERIOR chunks — whose fixed-size
  destination window provably stays inside the final run — skip the
  read half (their transient write tail is rewritten by the next
  chunk's window before any read); only boundary chunks, which can
  reach a neighbouring run/segment, keep the pair.  HBM traffic on the
  bulk of a big segment drops to ~2 reads + 2 writes per chunk —
  segment-proportional, never O(N).
* positions outside every segment are untouched in the raw output —
  the caller merges them back with the ``seg_id`` mask it already has
  (ops/partition.py does), same contract as v1.

With staging gone the dispatcher no longer needs a row cap:
``partition_rows`` takes this kernel at ANY N (the 650k fallback is
deleted; ``LGBMTPU_PARTITION_PALLAS=0`` and the degradation registry
remain the only opt-outs).

Validation status (honest): equivalence vs ``stable_partition_ranges``
is pinned in ``tests/test_partition.py`` through Mosaic INTERPRET mode —
this container has no TPU — including a slow-marked >650k-row case that
v1 could not reach.  The DMA constructs follow the accelerator guide's
double-buffering pattern; on-chip the expected ceiling is the scalar
compaction stores plus the serialized RMW DMA chain (4 DMAs on boundary
chunks, 2 on interior ones since the round-16 read-half skip), untuned.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_CHUNK = 512  # rows per DMA chunk; VPU-wide for the count phase, and the
# move phase's compaction loop stays short enough per chunk


def emit_move_sweep(order_hbm, go_hbm, out_hbm, obuf, gbuf, dbuf, sems,
                    start, seg_len, n_left):
    """One segment's MOVE sweep: stream order+go chunks (double-buffered),
    compact into the left/right runs, write back with boundary-RMW.

    THE shared routine between :func:`_partition_kernel` (which computes
    ``n_left`` with its count sweep first) and the round megakernel's
    partition phase (ops/round_pallas.py, where ``n_left`` arrives as a
    prefetched scalar) — one copy of the cursor/RMW logic, so a boundary
    fix or DMA tuning can never drift between the two kernels.  Expects
    the partition semaphore layout: ``sems[0:2]`` order chunks,
    ``sems[2:4]`` go chunks, ``sems[4]`` left run, ``sems[5]`` right run.
    """
    nc = pl.cdiv(seg_len, _CHUNK)

    def go_copy(c, slot):
        return pltpu.make_async_copy(
            go_hbm.at[:, pl.ds(start + c * _CHUNK, _CHUNK)],
            gbuf.at[slot], sems.at[2 + slot])

    def order_copy(c, slot):
        return pltpu.make_async_copy(
            order_hbm.at[:, pl.ds(start + c * _CHUNK, _CHUNK)],
            obuf.at[slot], sems.at[slot])

    @pl.when(nc > 0)
    def _warm_move():
        order_copy(0, 0).start()
        go_copy(0, 0).start()

    def move_body(c, cur):
        lcur, rcur = cur
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < nc)
        def _prefetch():
            order_copy(c + 1, 1 - slot).start()
            go_copy(c + 1, 1 - slot).start()

        order_copy(c, slot).wait()
        go_copy(c, slot).wait()
        m = jnp.minimum(seg_len - c * _CHUNK, _CHUNK)

        # left run RMW: read the destination window, overlay this chunk's
        # left rows from the cursor, write back (the tail past the overlay
        # is restored bit-for-bit, so the fixed-size DMA cannot clobber
        # the right run or a neighbouring segment).  INTERIOR chunks —
        # whose whole fixed-size window stays inside the final left run —
        # skip the read half (the round-12 queued follow-up): their write
        # tail is transient garbage that the NEXT chunk's window (which
        # starts exactly at this chunk's cursor frontier) fully rewrites
        # before anything reads it; only a window that can escape the run
        # (the boundary chunk) keeps the RMW pair.  Halves the serialized
        # DMA chain on the bulk of a big segment's chunks.
        @pl.when(lcur + _CHUNK > n_left)
        def _left_rd():
            left_rd = pltpu.make_async_copy(
                out_hbm.at[:, pl.ds(start + lcur, _CHUNK)], dbuf.at[0],
                sems.at[4])
            left_rd.start()
            left_rd.wait()

        def place_left(i, k):
            g = gbuf[slot, 0, i]

            @pl.when(g > 0)
            def _():
                dbuf[0, 0, k] = obuf[slot, 0, i]

            return k + g

        m_left = jax.lax.fori_loop(0, m, place_left, jnp.int32(0))
        left_wr = pltpu.make_async_copy(
            dbuf.at[0], out_hbm.at[:, pl.ds(start + lcur, _CHUNK)],
            sems.at[4])
        left_wr.start()
        left_wr.wait()

        # right run RMW (reads AFTER the left write retired: where the two
        # fixed-size windows overlap, the read sees the left run's final
        # bytes and the overlay/tail preserves them).  Same interior-chunk
        # skip, relative to the segment end: only the right window that
        # can reach past the segment (into a neighbour or untouched
        # positions) pays the read.
        @pl.when(n_left + rcur + _CHUNK > seg_len)
        def _right_rd():
            right_rd = pltpu.make_async_copy(
                out_hbm.at[:, pl.ds(start + n_left + rcur, _CHUNK)],
                dbuf.at[1], sems.at[5])
            right_rd.start()
            right_rd.wait()

        def place_right(i, k):
            g = gbuf[slot, 0, i]

            @pl.when(g == 0)
            def _():
                dbuf[1, 0, k] = obuf[slot, 0, i]

            return k + 1 - g

        m_right = jax.lax.fori_loop(0, m, place_right, jnp.int32(0))
        right_wr = pltpu.make_async_copy(
            dbuf.at[1], out_hbm.at[:, pl.ds(start + n_left + rcur, _CHUNK)],
            sems.at[5])
        right_wr.start()
        right_wr.wait()
        return (lcur + m_left, rcur + m_right)

    jax.lax.fori_loop(0, nc, move_body, (jnp.int32(0), jnp.int32(0)))


def _partition_kernel(seg_start_ref, seg_len_ref, order_hbm, go_hbm,
                      out_hbm, lc_ref, obuf, gbuf, dbuf, sems):
    """Grid (S,): one sequential step per segment.

    Scratch: ``obuf``/``gbuf`` (2, 1, _CHUNK) double-buffered input
    chunks (order / go_left), ``dbuf`` (2, 1, _CHUNK) destination RMW
    windows (left / right run), ``sems`` 6 DMA semaphores (order x2,
    go x2, left dst, right dst)."""
    s = pl.program_id(0)
    start = seg_start_ref[s]
    seg_len = seg_len_ref[s]
    nc = pl.cdiv(seg_len, _CHUNK)

    def go_copy(c, slot):
        return pltpu.make_async_copy(
            go_hbm.at[:, pl.ds(start + c * _CHUNK, _CHUNK)],
            gbuf.at[slot], sems.at[2 + slot])

    # ---- COUNT: stream go chunks (double-buffered), masked vector sum ----
    @pl.when(nc > 0)
    def _warm_count():
        go_copy(0, 0).start()

    def count_body(c, acc):
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < nc)
        def _prefetch():  # copy-in chunk c+1 while summing chunk c
            go_copy(c + 1, 1 - slot).start()

        go_copy(c, slot).wait()
        m = jnp.minimum(seg_len - c * _CHUNK, _CHUNK)
        iota = jax.lax.broadcasted_iota(jnp.int32, (1, _CHUNK), 1)
        return acc + jnp.sum(jnp.where(iota < m, gbuf[slot], 0))

    n_left = jax.lax.fori_loop(0, nc, count_body, jnp.int32(0))
    lc_ref[0, s] = n_left

    # ---- MOVE: the shared sweep (emit_move_sweep) ----
    emit_move_sweep(order_hbm, go_hbm, out_hbm, obuf, gbuf, dbuf, sems,
                    start, seg_len, n_left)


@functools.partial(jax.jit, static_argnames=("interpret",))
def partition_pallas_segments(
    order: jnp.ndarray,  # (N,) i32 — row ids, physically grouped by leaf
    seg_start: jnp.ndarray,  # (S,) i32 — start POSITION of each segment
    seg_len: jnp.ndarray,  # (S,) i32 — length (0 = inactive slot)
    go_left: jnp.ndarray,  # (N,) bool per POSITION
    *,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Stably partition every segment of ``order`` by ``go_left``.

    Returns ``(raw_order, left_counts)`` where ``raw_order`` holds the
    partitioned row ids INSIDE segments and the kernel's own untouched
    output elsewhere — merge with ``jnp.where(seg_id >= 0, raw_order,
    order)`` (the dispatcher in ops/partition.py does).  Segments must be
    disjoint.  No row cap: inputs stay HBM-resident (module docstring).
    """
    n = order.shape[0]
    S = seg_start.shape[0]
    # pad so every fixed-size chunk DMA is in range: a segment's last
    # chunk may reach up to CHUNK-1 past its end (<= n + CHUNK - 1), and
    # the RMW windows reach the same bound — out-of-range dynamic slices
    # CLAMP silently on TPU (docs/NEXT.md infra notes), so over-allocate
    # instead of relying on clamping
    n_pad = (pl.cdiv(n, _CHUNK) + 1) * _CHUNK
    order_p = jnp.pad(order, (0, n_pad - n))
    go_p = jnp.pad(go_left.astype(jnp.int32), (0, n_pad - n))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # order: HBM, DMA-chunked
            pl.BlockSpec(memory_space=pltpu.ANY),  # go_left: HBM
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # out: HBM, run-wise DMA
            # jaxlint: disable=R11 (left counts are O(S) segments — a few KB — not row-proportional; staging whole is the point)
            pl.BlockSpec((1, S), lambda s, *_: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, 1, _CHUNK), jnp.int32),  # order chunks (dbl-buf)
            pltpu.VMEM((2, 1, _CHUNK), jnp.int32),  # go chunks (dbl-buf)
            pltpu.VMEM((2, 1, _CHUNK), jnp.int32),  # left/right RMW windows
            pltpu.SemaphoreType.DMA((6,)),
        ],
    )
    raw, lc = pl.pallas_call(
        _partition_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((1, n_pad), order.dtype),
            jax.ShapeDtypeStruct((1, S), jnp.int32),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(seg_start.astype(jnp.int32), seg_len.astype(jnp.int32),
      order_p[None], go_p[None])
    return raw[0, :n], lc[0]
