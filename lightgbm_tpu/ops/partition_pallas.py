"""Pallas TPU kernel for the leaf-ordered row partition.

The XLA implementation (ops/partition.py::stable_partition_ranges) is
exact but pays O(N) regardless of how few rows a round actually splits:
two full-N cumsums plus a full-N permutation scatter measured ~41 ms per
1M-row round on a v5e — pure fixed cost from the windowed grower's admit
phase (docs/NEXT.md round-6 lever 1).  A round only *moves* the rows
inside its split segments (the parents of this round's splits, at most
2x the round's window), so the data movement should be window-
proportional, like the reference's in-place ``DataPartition::Split``
(src/treelearner/data_partition.hpp) which touches only the split leaf's
``[start, count)`` index range.

This kernel is that in-place split, vectorized over all of a round's
split segments:

* grid ``(S, 2, C)`` — per segment, a COUNT phase then a MOVE phase,
  each sweeping fixed-size chunks; TPU grids execute sequentially, so
  per-segment running counters live in SMEM scratch across chunks.
* count phase: vectorized masked sum of ``go_left`` over the segment's
  chunks -> ``n_left`` (needed before any element can be placed).
* move phase: a chunk-local ``fori_loop`` placing each row id at
  ``start + left_rank`` / ``start + n_left + right_rank``.  Stability is
  inherited from the sequential sweep.
* compute scales with the segments: chunks past ``seg_len`` are
  ``pl.when``-skipped, so count-phase vector work and move-phase loop
  trips are proportional to the segment total, not N.  STAGING is still
  O(N): the v1 kernel keeps order/go/out as whole-array VMEM blocks
  (~12 bytes/row across the three buffers), which is cheap next to the
  2 cumsums + permutation scatter it replaces but caps N at the scoped
  VMEM budget — the dispatcher (ops/partition.py::partition_rows) falls
  back to the XLA path above ``_MAX_VMEM_ROWS`` rows, and an
  HBM-resident variant with explicit per-chunk DMA is the documented
  round-8 refinement (docs/NEXT.md).  Positions outside every segment
  are left undefined in the raw output — the caller merges them back
  with the ``seg_id`` mask it already has.

Validation status (honest): equivalence vs ``stable_partition_ranges``
is pinned in ``tests/test_partition.py`` through Mosaic INTERPRET mode —
this container has no TPU.  The kernel compiles from constructs the
toolchain accepts elsewhere in the repo (scalar prefetch, SMEM scratch,
``pl.when``, dynamic ``pl.ds``), but the scalar-store move loop is
untuned; on-chip the expected ceiling is SREG-bound element placement
(~segment_rows scalar stores), which still beats the full-N scatter once
windows are < ~N/4.  ``LGBMTPU_PARTITION_PALLAS=0`` falls back to the
XLA path without retracing semantics (ops/treegrow_windowed.py reads it
at trace time).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_CHUNK = 512  # rows per grid step; VPU-wide for the count phase, and the
# move phase's fori_loop body stays short enough to unroll per chunk

# v1 stages order/go/out as full-array VMEM blocks: 3 buffers x 4 bytes x
# n_pad must fit the ~16 MiB scoped-VMEM cap with headroom — above this
# the dispatcher uses the XLA path (Epsilon's 400k rows fit; 1M does not)
_MAX_VMEM_ROWS = 650_000


def _partition_kernel(seg_start_ref, seg_len_ref, order_ref, go_ref,
                      out_ref, lc_ref, carry):
    """Grid (S, 2, C): segment s, phase (0=count, 1=move), chunk c.

    carry (SMEM, i32): [0] n_left of the current segment, [1] left write
    cursor, [2] right write cursor — valid across chunks because the TPU
    grid is sequential (phase/chunk iterate fastest)."""
    s = pl.program_id(0)
    ph = pl.program_id(1)
    c = pl.program_id(2)
    start = seg_start_ref[s]
    base = start + c * _CHUNK
    rem = seg_len_ref[s] - c * _CHUNK

    @pl.when((ph == 0) & (c == 0))
    def _reset_count():
        carry[0] = 0

    @pl.when((ph == 0) & (rem > 0))
    def _count():
        m = jnp.minimum(rem, _CHUNK)
        vals = go_ref[:, pl.ds(base, _CHUNK)]  # (1, CHUNK) i32 0/1
        iota = jax.lax.broadcasted_iota(jnp.int32, (1, _CHUNK), 1)
        carry[0] += jnp.sum(jnp.where(iota < m, vals, 0))

    @pl.when((ph == 1) & (c == 0))
    def _start_move():
        lc_ref[0, s] = carry[0]
        carry[1] = 0
        carry[2] = 0

    @pl.when((ph == 1) & (rem > 0))
    def _move():
        m = jnp.minimum(rem, _CHUNK)
        n_left = carry[0]

        def place(i, cur):
            left_cur, right_cur = cur
            g = go_ref[0, base + i]
            dest = jnp.where(g > 0, start + left_cur,
                             start + n_left + right_cur)
            out_ref[0, dest] = order_ref[0, base + i]
            return (left_cur + g, right_cur + 1 - g)

        left_cur, right_cur = jax.lax.fori_loop(
            0, m, place, (carry[1], carry[2]))
        carry[1] = left_cur
        carry[2] = right_cur


@functools.partial(jax.jit, static_argnames=("interpret",))
def partition_pallas_segments(
    order: jnp.ndarray,  # (N,) i32 — row ids, physically grouped by leaf
    seg_start: jnp.ndarray,  # (S,) i32 — start POSITION of each segment
    seg_len: jnp.ndarray,  # (S,) i32 — length (0 = inactive slot)
    go_left: jnp.ndarray,  # (N,) bool per POSITION
    *,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Stably partition every segment of ``order`` by ``go_left``.

    Returns ``(raw_order, left_counts)`` where ``raw_order`` holds the
    partitioned row ids INSIDE segments and undefined values outside —
    merge with ``jnp.where(seg_id >= 0, raw_order, order)`` (the
    dispatcher in ops/partition.py does).  Segments must be disjoint.
    """
    n = order.shape[0]
    S = seg_start.shape[0]
    C = pl.cdiv(n, _CHUNK)
    # pad so every chunk slice is in range: a segment's last chunk may
    # slice up to CHUNK-1 past N, and an out-of-range pl.ds start CLAMPS
    # (silently reading shifted data) — the iota<rem mask then does the
    # real bounding against the padded tail
    n_pad = (C + 1) * _CHUNK
    order_p = jnp.pad(order, (0, n_pad - n))
    go_p = jnp.pad(go_left.astype(jnp.int32), (0, n_pad - n))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, 2, C),
        in_specs=[
            pl.BlockSpec((1, n_pad), lambda s, p, c, *_: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n_pad), lambda s, p, c, *_: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, n_pad), lambda s, p, c, *_: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S), lambda s, p, c, *_: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[pltpu.SMEM((4,), jnp.int32)],
    )
    raw, lc = pl.pallas_call(
        _partition_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((1, n_pad), order.dtype),
            jax.ShapeDtypeStruct((1, S), jnp.int32),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(seg_start.astype(jnp.int32), seg_len.astype(jnp.int32),
      order_p[None], go_p[None])
    return raw[0, :n], lc[0]
