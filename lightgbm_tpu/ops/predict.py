"""Vectorized tree-ensemble prediction.

TPU-native replacement for per-row tree traversal
(reference: src/io/tree.cpp -> Tree::Prediction / NumericalDecision /
Tree::AddPredictionToScore, src/boosting/gbdt_prediction.cpp -> GBDT::PredictRaw).

The reference walks each tree with scalar pointer chasing per row.  Here all
rows advance one level per step through a structure-of-arrays tree, with a
`lax.while_loop` that stops when every row has reached a leaf — gathers over
node arrays, no data-dependent Python control flow.

Trees are stacked: ensembles predict via one vmapped traversal over the tree
axis then a sum reduction, keeping the MXU/VPU busy across trees.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _traverse_one_tree(
    feature_vals: jnp.ndarray,  # (N, F) raw float values OR binned ints as f32
    is_missing: jnp.ndarray,  # (N, F) bool (NaN in the raw input)
    split_feature: jnp.ndarray,  # (M,) i32
    threshold: jnp.ndarray,  # (M,) f32 — decision `value <= threshold` -> left
    default_left: jnp.ndarray,  # (M,) bool
    missing_type: jnp.ndarray,  # (M,) i32: 0=None, 1=Zero, 2=NaN
    left_child: jnp.ndarray,  # (M,) i32 (negative = ~leaf)
    right_child: jnp.ndarray,  # (M,) i32
    num_leaves: jnp.ndarray,  # i32 scalar
    is_cat: jnp.ndarray = None,  # (M,) bool — categorical nodes
    cat_base: jnp.ndarray = None,  # (M,) i32 word offset into cat_words
    cat_nwords: jnp.ndarray = None,  # (M,) i32
    cat_words: jnp.ndarray = None,  # (W,) uint32 flat bitsets
) -> jnp.ndarray:
    """Returns leaf index per row.

    Decision semantics per node missing_type (reference:
    Tree::NumericalDecision in include/LightGBM/tree.h):
      NaN:  NaN -> default direction; else value <= threshold
      Zero: NaN or |value| <= kZeroThreshold -> default; else compare
      None: NaN treated as 0.0, then compare
    Categorical nodes (reference: Tree::CategoricalDecision): value in the
    node's bitset -> left; NaN/negative/out-of-range -> right.
    """
    n = feature_vals.shape[0]
    k_zero = jnp.float32(1e-35)

    def cond(carry):
        node, _ = carry
        return jnp.any(node >= 0)

    def step(carry):
        node, leaf = carry
        nd = jnp.maximum(node, 0)
        f = split_feature[nd]
        v = jnp.take_along_axis(feature_vals, f[:, None], axis=1)[:, 0]
        miss = jnp.take_along_axis(is_missing, f[:, None], axis=1)[:, 0]
        mt = missing_type[nd]
        use_default = jnp.where(
            mt == 2, miss, jnp.where(mt == 1, miss | (jnp.abs(v) <= k_zero), False)
        )
        v_eff = jnp.where(miss, 0.0, v)  # mt 0/1 non-default path: NaN -> 0.0
        go_left = jnp.where(use_default, default_left[nd], v_eff <= threshold[nd])
        if is_cat is not None:
            iv = v_eff.astype(jnp.int32)  # C-cast truncation like the reference
            w = iv >> 5
            in_range = (~miss) & (iv >= 0) & (w < cat_nwords[nd])
            widx = jnp.clip(cat_base[nd] + w, 0, cat_words.shape[0] - 1)
            word = cat_words[widx]
            bit = (word >> (iv & 31).astype(jnp.uint32)) & jnp.uint32(1)
            go_left = jnp.where(is_cat[nd], in_range & (bit == 1), go_left)
        nxt = jnp.where(go_left, left_child[nd], right_child[nd])
        at_internal = node >= 0
        new_node = jnp.where(at_internal, nxt, node)
        new_leaf = jnp.where(at_internal & (new_node < 0), -new_node - 1, leaf)
        return new_node, new_leaf

    # single-leaf tree (no splits): every row lands in leaf 0
    node0 = jnp.where(num_leaves > 1, jnp.zeros((n,), jnp.int32), -1)
    leaf0 = jnp.zeros((n,), jnp.int32)
    _, leaf = jax.lax.while_loop(cond, step, (node0, leaf0))
    return leaf


@functools.partial(jax.jit, static_argnames=())
def predict_leaf_binned(
    bins: jnp.ndarray,  # (N, F) int
    missing_bin_per_feature: jnp.ndarray,  # (F,) i32
    split_feature: jnp.ndarray,  # (T, M)
    threshold_bin: jnp.ndarray,  # (T, M) i32
    default_left: jnp.ndarray,  # (T, M)
    left_child: jnp.ndarray,  # (T, M)
    right_child: jnp.ndarray,  # (T, M)
    num_leaves: jnp.ndarray,  # (T,)
) -> jnp.ndarray:
    """Leaf index per (tree, row) on BINNED data: (T, N) i32."""
    vals = bins.astype(jnp.float32)
    miss = bins == missing_bin_per_feature[None, :]
    # binned space: the missing bin is exact, so every node behaves as
    # missing_type=NaN over the `miss` mask
    fn = jax.vmap(
        lambda sf, th, dl, lc, rc, nl: _traverse_one_tree(
            vals, miss, sf, th.astype(jnp.float32), dl,
            jnp.full(sf.shape, 2, jnp.int32), lc, rc, nl
        )
    )
    return fn(split_feature, threshold_bin, default_left, left_child, right_child, num_leaves)


@functools.partial(jax.jit, static_argnames=())
def predict_raw_values(
    x: jnp.ndarray,  # (N, F) f32/f64 raw features (NaN = missing)
    split_feature: jnp.ndarray,  # (T, M)
    threshold: jnp.ndarray,  # (T, M) real-valued thresholds
    default_left: jnp.ndarray,
    missing_type: jnp.ndarray,  # (T, M) i32
    left_child: jnp.ndarray,
    right_child: jnp.ndarray,
    num_leaves: jnp.ndarray,
    leaf_value: jnp.ndarray,  # (T, L)
    is_cat: jnp.ndarray = None,  # (T, M) bool
    cat_base: jnp.ndarray = None,  # (T, M) i32 into cat_words
    cat_nwords: jnp.ndarray = None,  # (T, M) i32
    cat_words: jnp.ndarray = None,  # (W,) uint32
) -> jnp.ndarray:
    """Raw ensemble margin per row: sum over trees of leaf values (N,)."""
    x = x.astype(jnp.float32)
    miss = jnp.isnan(x)
    vals = jnp.where(miss, 0.0, x)

    if is_cat is None:
        def one(sf, th, dl, mt, lc, rc, nl, lv):
            leaf = _traverse_one_tree(vals, miss, sf, th.astype(jnp.float32), dl, mt, lc, rc, nl)
            return lv[leaf]

        per_tree = jax.vmap(one)(
            split_feature, threshold, default_left, missing_type, left_child,
            right_child, num_leaves, leaf_value,
        )  # (T, N)
    else:
        def one_cat(sf, th, dl, mt, lc, rc, nl, lv, ic, cb, cw):
            leaf = _traverse_one_tree(
                vals, miss, sf, th.astype(jnp.float32), dl, mt, lc, rc, nl,
                is_cat=ic, cat_base=cb, cat_nwords=cw, cat_words=cat_words)
            return lv[leaf]

        per_tree = jax.vmap(one_cat)(
            split_feature, threshold, default_left, missing_type, left_child,
            right_child, num_leaves, leaf_value, is_cat, cat_base, cat_nwords,
        )
    return jnp.sum(per_tree, axis=0)
