"""Vectorized tree-ensemble prediction.

TPU-native replacement for per-row tree traversal
(reference: src/io/tree.cpp -> Tree::Prediction / NumericalDecision /
Tree::AddPredictionToScore, src/boosting/gbdt_prediction.cpp -> GBDT::PredictRaw).

The reference walks each tree with scalar pointer chasing per row.  Here all
rows advance one level per step through a structure-of-arrays tree, with a
`lax.while_loop` that stops when every row has reached a leaf — gathers over
node arrays, no data-dependent Python control flow.

Trees are stacked: ensembles predict via one vmapped traversal over the tree
axis then a sum reduction, keeping the MXU/VPU busy across trees.

Serving entry points (round 9) are shape-stable and one-dispatch:

* every op takes an optional ``active`` row mask so callers can pad the row
  axis to a bucket ladder (models/gbdt.py ``_predict_bucket``) and mask the
  padding / early-stopped rows ON DEVICE — the executable is reused across
  batch sizes and early-stop chunks instead of recompiling per distinct N;
* :func:`predict_raw_multiclass` folds the per-class host loop (k separate
  dispatches) into one class-reshaped reduction — one dispatch per call;
* :func:`predict_raw_window` traverses a fixed-size window of trees starting
  at a TRACED offset (``lax.dynamic_slice_in_dim``), so prediction
  early-stopping runs every chunk through the SAME compiled executable;
* :func:`predict_leaf_values` is the stacked device traversal behind
  ``pred_leaf`` (previously a per-tree host walk).

Telemetry contract (round 10, docs/OBSERVABILITY.md): these ops are pure
traced programs and carry NO instrumentation — the serving layer
(models/gbdt.py ``_serve_t0``/``_serve_note``) times each entry point at
its accounted ``sync_pull``, where the device queue has provably drained,
and feeds the ``predict_warm_latency_ms`` reservoirs.  Adding host-side
counters or timers INSIDE these jitted bodies would either break the trace
or run once at trace time (jaxlint R5); timing around them without the
sync is the jaxlint-R9 mistiming class.

IR contract (round 15): the warm entries are pinned on the traced jaxpr
by the ``predict_warm_single`` / ``_multiclass`` / ``_converted`` audit
contracts (analysis/contracts.py, tests/test_jaxpr_audit.py) —
collective-free, callback-free, f64-free bodies with no oversized baked
constants and a bounded live set; a per-class host loop or an in-trace
transfer reappearing here fails the audit statically.

Serving-loop contract (round 18): the continuous-batching runtime
(lightgbm_tpu/serve) dispatches coalesced batches through THESE SAME
functions — ``GBDT._coalesced_raw_fn`` selects them, and the
``predict_coalesced_bucket`` contract traces that selection — so the
serving loop shares the bucket ladder's compiled executables and can
never silently grow a second dispatch family.  Adding a serve-only
entry here (or in serve/) breaks that contract's audit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _traverse_one_tree(
    feature_vals: jnp.ndarray,  # (N, F) raw float values OR binned ints as f32
    is_missing: jnp.ndarray,  # (N, F) bool (NaN in the raw input)
    split_feature: jnp.ndarray,  # (M,) i32
    threshold: jnp.ndarray,  # (M,) f32 — decision `value <= threshold` -> left
    default_left: jnp.ndarray,  # (M,) bool
    missing_type: jnp.ndarray,  # (M,) i32: 0=None, 1=Zero, 2=NaN
    left_child: jnp.ndarray,  # (M,) i32 (negative = ~leaf)
    right_child: jnp.ndarray,  # (M,) i32
    num_leaves: jnp.ndarray,  # i32 scalar
    is_cat: jnp.ndarray = None,  # (M,) bool — categorical nodes
    cat_base: jnp.ndarray = None,  # (M,) i32 word offset into cat_words
    cat_nwords: jnp.ndarray = None,  # (M,) i32
    cat_words: jnp.ndarray = None,  # (W,) uint32 flat bitsets
) -> jnp.ndarray:
    """Returns leaf index per row.

    Decision semantics per node missing_type (reference:
    Tree::NumericalDecision in include/LightGBM/tree.h):
      NaN:  NaN -> default direction; else value <= threshold
      Zero: NaN or |value| <= kZeroThreshold -> default; else compare
      None: NaN treated as 0.0, then compare
    Categorical nodes (reference: Tree::CategoricalDecision): value in the
    node's bitset -> left; NaN/negative/out-of-range -> right.
    """
    n = feature_vals.shape[0]
    k_zero = jnp.float32(1e-35)

    def cond(carry):
        node, _ = carry
        return jnp.any(node >= 0)

    def step(carry):
        node, leaf = carry
        nd = jnp.maximum(node, 0)
        f = split_feature[nd]
        v = jnp.take_along_axis(feature_vals, f[:, None], axis=1)[:, 0]
        miss = jnp.take_along_axis(is_missing, f[:, None], axis=1)[:, 0]
        mt = missing_type[nd]
        use_default = jnp.where(
            mt == 2, miss, jnp.where(mt == 1, miss | (jnp.abs(v) <= k_zero), False)
        )
        v_eff = jnp.where(miss, 0.0, v)  # mt 0/1 non-default path: NaN -> 0.0
        go_left = jnp.where(use_default, default_left[nd], v_eff <= threshold[nd])
        if is_cat is not None:
            iv = v_eff.astype(jnp.int32)  # C-cast truncation like the reference
            w = iv >> 5
            in_range = (~miss) & (iv >= 0) & (w < cat_nwords[nd])
            widx = jnp.clip(cat_base[nd] + w, 0, cat_words.shape[0] - 1)
            word = cat_words[widx]
            bit = (word >> (iv & 31).astype(jnp.uint32)) & jnp.uint32(1)
            go_left = jnp.where(is_cat[nd], in_range & (bit == 1), go_left)
        nxt = jnp.where(go_left, left_child[nd], right_child[nd])
        at_internal = node >= 0
        new_node = jnp.where(at_internal, nxt, node)
        new_leaf = jnp.where(at_internal & (new_node < 0), -new_node - 1, leaf)
        return new_node, new_leaf

    # single-leaf tree (no splits): every row lands in leaf 0
    node0 = jnp.where(num_leaves > 1, jnp.zeros((n,), jnp.int32), -1)
    leaf0 = jnp.zeros((n,), jnp.int32)
    _, leaf = jax.lax.while_loop(cond, step, (node0, leaf0))
    return leaf


@functools.partial(jax.jit, static_argnames=())
def predict_leaf_binned(
    bins: jnp.ndarray,  # (N, F) int
    missing_bin_per_feature: jnp.ndarray,  # (F,) i32
    split_feature: jnp.ndarray,  # (T, M)
    threshold_bin: jnp.ndarray,  # (T, M) i32
    default_left: jnp.ndarray,  # (T, M)
    left_child: jnp.ndarray,  # (T, M)
    right_child: jnp.ndarray,  # (T, M)
    num_leaves: jnp.ndarray,  # (T,)
) -> jnp.ndarray:
    """Leaf index per (tree, row) on BINNED data: (T, N) i32."""
    vals = bins.astype(jnp.float32)
    miss = bins == missing_bin_per_feature[None, :]
    # binned space: the missing bin is exact, so every node behaves as
    # missing_type=NaN over the `miss` mask
    fn = jax.vmap(
        lambda sf, th, dl, lc, rc, nl: _traverse_one_tree(
            vals, miss, sf, th.astype(jnp.float32), dl,
            jnp.full(sf.shape, 2, jnp.int32), lc, rc, nl
        )
    )
    return fn(split_feature, threshold_bin, default_left, left_child, right_child, num_leaves)


def _per_tree_values(
    x: jnp.ndarray,  # (N, F) raw features (NaN = missing)
    split_feature, threshold, default_left, missing_type, left_child,
    right_child, num_leaves,
    leaf_value=None,  # (T, L) — None returns leaf INDICES instead of values
    is_cat=None, cat_base=None, cat_nwords=None, cat_words=None,
) -> jnp.ndarray:
    """Vmapped traversal over the stacked tree axis: (T, N) leaf values
    (or leaf indices when ``leaf_value`` is None)."""
    x = x.astype(jnp.float32)
    miss = jnp.isnan(x)
    vals = jnp.where(miss, 0.0, x)

    if is_cat is None:
        def one(sf, th, dl, mt, lc, rc, nl):
            return _traverse_one_tree(
                vals, miss, sf, th.astype(jnp.float32), dl, mt, lc, rc, nl)

        leaf = jax.vmap(one)(
            split_feature, threshold, default_left, missing_type, left_child,
            right_child, num_leaves,
        )  # (T, N)
    else:
        def one_cat(sf, th, dl, mt, lc, rc, nl, ic, cb, cw):
            return _traverse_one_tree(
                vals, miss, sf, th.astype(jnp.float32), dl, mt, lc, rc, nl,
                is_cat=ic, cat_base=cb, cat_nwords=cw, cat_words=cat_words)

        leaf = jax.vmap(one_cat)(
            split_feature, threshold, default_left, missing_type, left_child,
            right_child, num_leaves, is_cat, cat_base, cat_nwords,
        )
    if leaf_value is None:
        return leaf
    return jnp.take_along_axis(leaf_value, leaf, axis=1)  # (T, N)


@functools.partial(jax.jit, static_argnames=())
def predict_raw_values(
    x: jnp.ndarray,  # (N, F) f32/f64 raw features (NaN = missing)
    split_feature: jnp.ndarray,  # (T, M)
    threshold: jnp.ndarray,  # (T, M) real-valued thresholds
    default_left: jnp.ndarray,
    missing_type: jnp.ndarray,  # (T, M) i32
    left_child: jnp.ndarray,
    right_child: jnp.ndarray,
    num_leaves: jnp.ndarray,
    leaf_value: jnp.ndarray,  # (T, L)
    is_cat: jnp.ndarray = None,  # (T, M) bool
    cat_base: jnp.ndarray = None,  # (T, M) i32 into cat_words
    cat_nwords: jnp.ndarray = None,  # (T, M) i32
    cat_words: jnp.ndarray = None,  # (W,) uint32
    active: jnp.ndarray = None,  # (N,) bool — inactive/padding rows emit 0
) -> jnp.ndarray:
    """Raw ensemble margin per row: sum over trees of leaf values (N,)."""
    per_tree = _per_tree_values(
        x, split_feature, threshold, default_left, missing_type, left_child,
        right_child, num_leaves, leaf_value,
        is_cat=is_cat, cat_base=cat_base, cat_nwords=cat_nwords,
        cat_words=cat_words,
    )
    out = jnp.sum(per_tree, axis=0)
    if active is not None:
        out = jnp.where(active, out, 0.0)
    return out


@functools.partial(jax.jit, static_argnames=("k",))
def predict_raw_multiclass(
    x: jnp.ndarray,  # (N, F)
    split_feature: jnp.ndarray,  # (T, M) — T trees, iter-major class-minor
    threshold: jnp.ndarray,
    default_left: jnp.ndarray,
    missing_type: jnp.ndarray,
    left_child: jnp.ndarray,
    right_child: jnp.ndarray,
    num_leaves: jnp.ndarray,
    leaf_value: jnp.ndarray,  # (T, L)
    is_cat: jnp.ndarray = None,
    cat_base: jnp.ndarray = None,
    cat_nwords: jnp.ndarray = None,
    cat_words: jnp.ndarray = None,
    active: jnp.ndarray = None,  # (N,) bool
    *,
    k: int,
) -> jnp.ndarray:
    """Multiclass raw margins in ONE dispatch: (N, k).

    Tree i belongs to class ``i % k`` (the flat iter-major layout), so the
    per-tree values reshape to (T//k, k, N) and reduce over the iteration
    axis — each class sums its own trees in the same order as a per-class
    slice, which keeps the result bit-identical to the k-dispatch host loop
    this op replaced (gbdt.py round-6 predict_raw)."""
    per_tree = _per_tree_values(
        x, split_feature, threshold, default_left, missing_type, left_child,
        right_child, num_leaves, leaf_value,
        is_cat=is_cat, cat_base=cat_base, cat_nwords=cat_nwords,
        cat_words=cat_words,
    )  # (T, N)
    t, n = per_tree.shape
    out = jnp.sum(per_tree.reshape(t // k, k, n), axis=0)  # (k, N)
    if active is not None:
        out = jnp.where(active[None, :], out, 0.0)
    return out.T  # (N, k)


@functools.partial(jax.jit, static_argnames=("k", "window"))
def predict_raw_window(
    x: jnp.ndarray,  # (N, F)
    tree_lo: jnp.ndarray,  # i32 scalar, TRACED — first tree of the window
    split_feature: jnp.ndarray,  # (Tp, M) — Tp padded to a multiple of window
    threshold: jnp.ndarray,
    default_left: jnp.ndarray,
    missing_type: jnp.ndarray,
    left_child: jnp.ndarray,
    right_child: jnp.ndarray,
    num_leaves: jnp.ndarray,
    leaf_value: jnp.ndarray,  # (Tp, L)
    is_cat: jnp.ndarray = None,
    cat_base: jnp.ndarray = None,
    cat_nwords: jnp.ndarray = None,
    cat_words: jnp.ndarray = None,  # (W,) flat — NOT sliced (global offsets)
    active: jnp.ndarray = None,  # (N,) bool — early-stopped rows emit 0
    *,
    k: int,
    window: int,
) -> jnp.ndarray:
    """Raw margins of ``window`` consecutive trees starting at ``tree_lo``:
    (N,) for k == 1, else (N, k).

    The window size is static but the offset is traced, so prediction
    early-stopping dispatches every chunk through ONE compiled executable —
    the caller pads the tree axis with single-leaf zero-value trees
    (gbdt.py ``_packed(pad_trees_to=...)``) so the slice is always in
    range."""
    def win(a):
        return (None if a is None
                else jax.lax.dynamic_slice_in_dim(a, tree_lo, window, axis=0))

    per_tree = _per_tree_values(
        x, win(split_feature), win(threshold), win(default_left),
        win(missing_type), win(left_child), win(right_child),
        win(num_leaves), win(leaf_value),
        is_cat=win(is_cat), cat_base=win(cat_base), cat_nwords=win(cat_nwords),
        cat_words=cat_words,
    )  # (window, N)
    n = per_tree.shape[1]
    if k == 1:
        out = jnp.sum(per_tree, axis=0)  # (N,)
        if active is not None:
            out = jnp.where(active, out, 0.0)
        return out
    out = jnp.sum(per_tree.reshape(window // k, k, n), axis=0)  # (k, N)
    if active is not None:
        out = jnp.where(active[None, :], out, 0.0)
    return out.T


@functools.partial(jax.jit, static_argnames=())
def predict_leaf_values(
    x: jnp.ndarray,  # (N, F) raw features (NaN = missing)
    split_feature: jnp.ndarray,  # (T, M)
    threshold: jnp.ndarray,
    default_left: jnp.ndarray,
    missing_type: jnp.ndarray,
    left_child: jnp.ndarray,
    right_child: jnp.ndarray,
    num_leaves: jnp.ndarray,
    is_cat: jnp.ndarray = None,
    cat_base: jnp.ndarray = None,
    cat_nwords: jnp.ndarray = None,
    cat_words: jnp.ndarray = None,
) -> jnp.ndarray:
    """Leaf index per (row, tree) on RAW values: (N, T) i32 — the stacked
    device traversal behind ``pred_leaf`` (reference: Predictor's leaf-index
    mode; previously a per-tree host walk)."""
    leaf = _per_tree_values(
        x, split_feature, threshold, default_left, missing_type, left_child,
        right_child, num_leaves, None,
        is_cat=is_cat, cat_base=cat_base, cat_nwords=cat_nwords,
        cat_words=cat_words,
    )  # (T, N)
    return leaf.T
