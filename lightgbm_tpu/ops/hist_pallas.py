"""Pallas TPU histogram kernels — the hot op of GBDT training.

TPU-native replacement for the reference's histogram inner loops
(reference: src/treelearner/cuda/cuda_histogram_constructor.cu,
src/io/dense_bin.hpp -> DenseBin::ConstructHistogram).  The CUDA kernel
accumulates into shared-memory atomics; TPUs have no atomics, so the
histogram is a one-hot matmul on the MXU with a VMEM accumulator that lives
across a sequential row-tile grid (SURVEY.md §10.1 strategy 2): per feature,
onehot(bin) in {0,1}^(T,B) is contracted against a (T, NC) payload.

Measured design notes (in-jit fori_loop probes on a v5e chip, N=1M F=28;
methodology + full numbers in docs/PERF_NOTES.md):

* A full-N pass costs ~8-10 ms and is INVARIANT to num_bins, payload
  lanes, row tile and bins layout — the floor is the per-(tile, feature)
  dot on this toolchain, NOT the one-hot build.  A hi/lo bin-decomposition
  variant (8x fewer MXU passes) measured 3x SLOWER; a pure-XLA one-hot
  einsum (ops/histogram.py::histogram_onehot_multi) beats this kernel at
  num_bins <= 64 (~3 ms) and loses above it — the grower selects per
  max_bin.
* Payload lanes are nearly free up to the 128-lane MXU tile: the (NC, B)
  output occupies the same MXU tiles for NC in 4..128.  Near-f32 precision
  therefore costs the same as bf16: the payload is split hi+lo bfloat16
  (bf16x2) into 8 lanes and recombined after accumulation.  hi is exact in
  bf16; lo is rounded to bf16, so products carry ~16-17 mantissa bits (vs 8
  for plain bf16, 24 for true f32) and accumulation is f32 — between the
  reference's float-hist and double-hist modes in practice.
* The same free-lane property batches MULTIPLE histograms in one pass:
  `histogram_pallas_multi` computes per-leaf histograms for up to 15 leaves
  (channels = leaf one-hot x payload) in a single data pass — the engine of
  the level-batched grower.
* Mosaic on this toolchain rejects bf16/int8 broadcast-selects (and int8
  compares); everything is built in 32-bit dtypes and cast at the dot.  The
  multi-leaf kernels measured ~20% faster at a 1024-row tile (verified to
  compile and run on-chip); the select-heavy experimental kernels that
  motivated the earlier 512 cap were removed after losing the benchmark.

Channels convention of the package: CHANNEL-FIRST (3, F, B) with channels
(sum_grad, sum_hess, count).  Channel-first is a measured TPU layout
decision (docs/PERF_NOTES.md round 4/5): a trailing dim of 3 forces XLA's
tiled layouts to pad the minor pair (B, 3) -> (B, 128) = 42.7x memory in
every hist copy/scatter; with (3, F, B) the minor tile pair (F, B) pads
~nothing at real shapes.  The reference makes the same device-driven
layout choice in src/treelearner/cuda/cuda_histogram_constructor.cu
(grad/hess interleaving picked for the GPU, not the host).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# Bytes of VMEM accumulator headroom shared by the histogram leaf-tile
# policy (recommended_leaf_tile below) AND the round megakernel's
# feature-block sizing (ops/round_pallas.py::megakernel_feature_block) —
# ONE budget so the two VMEM cost models can never drift apart.
VMEM_ACC_BUDGET = 8_000_000


def payload_channels(hist_precision: str, quantized: bool) -> int:
    """Payload lanes per leaf for the multi-leaf kernels: 6 for the
    bf16x2-split f32 path, 3 for rounded bf16 or int8-quantized."""
    return 3 if (quantized or hist_precision == "bf16") else 6


def recommended_leaf_tile(
    num_bins: int,
    n_features_effective: int,
    num_leaves: int,
    *,
    hist_precision: str = "f32",
    quantized: bool = False,
) -> int:
    """Leaves per multi-leaf pass for THIS module's kernels — the
    channel-aware tile selection, kept next to the VMEM cost model it
    budgets against (round 7; previously inlined in models/gbdt.py).

    Wide data runs one pallas_call per 128-feature chunk, so the VMEM
    accumulator — the binding constraint — is (min(F,128), lanes, B) f32
    regardless of total F; lanes beyond ~64 also measurably slow the dot
    (benchmarks/probe_b256b/c), so the wide-data budget is ~60 payload
    lanes: 10 leaves x 6ch float, or 20 leaves x 3ch quantized (the int
    path needs no bf16x2 split — half the lanes per leaf buys half the
    admission rounds).

    Narrow data (one feature chunk) is pass-count-bound, not lane-bound:
    the measured optimum is ~48-60 payload lanes — 8 leaves for the
    6-channel bf16x2 payload, 16 for 3-channel bf16, 20 for 3-lane int8
    (the tile16-bf16 / tile20-q16 configurations of
    benchmarks/probe_narrow255.py; docs/PERF_NOTES.md round 7 has the
    255-bin floor analysis they probe against).
    """
    ncl = payload_channels(hist_precision, quantized)
    fb = min(n_features_effective if n_features_effective > 0 else 1, 128)
    fb_pad = max(_round_up(fb, 8), 8)
    budget = VMEM_ACC_BUDGET  # shared with the megakernel (module const)
    bpad = _round_up(max(num_bins, 8), 8)  # kernel pads B to 8
    per_leaf = fb_pad * bpad * 4 * ncl  # f32/int32 accumulator lanes
    if n_features_effective <= 128:
        cap = 8 if ncl == 6 else (20 if quantized else 16)
    else:
        cap = 20 if quantized else 10  # both = ~60 lanes
    return max(1, min(cap, budget // max(per_leaf, 1), num_leaves))


_FEAT_BLOCK = 128  # feature-block width for wide datasets (Epsilon-class);
# Mosaic requires trailing block dims divisible by 128 (or the full array
# width, which covers every narrow dataset)


def _direct_kernel(bins_ref, pay_ref, out_ref, *, FB, B, NC, dtype):
    """Grid (feature_blocks, row_tiles); row tiles iterate fastest, so the
    accumulator lives across the row sweep of one feature block.

    Measured cost model (in-jit fori_loop probes past the ~23 ms tunnel
    dispatch floor, v5e): a full-N pass costs ~7.7-10 ms at N=1M, F=28 and
    is INVARIANT to num_bins (64 vs 256), payload lanes (8 vs 48), row
    tile (1024-8192), bins layout (row- vs feature-major), and even to
    replacing the one-hot compare with a constant — the floor is the
    per-(tile, feature) dot itself.  Consequence: payload lanes up to the
    128-wide MXU tile are FREE; fill them (21 leaves x 6ch) and cut the
    number of passes, do not shrink B or NC."""
    i = pl.program_id(1)

    # the revisited output block IS the accumulator (a separate VMEM
    # scratch would double the scoped footprint and OOM at 60 lanes x 256
    # bins x 128 features — measured 17.04M vs the 16M cap)
    @pl.when(i == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    pay = pay_ref[...].astype(dtype)  # (T, NC)
    T = pay.shape[0]
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (T, B), 1)  # hoisted
    bins_i32 = bins_ref[...].astype(jnp.int32)  # (T, FB) upcast once
    for f in range(FB):
        binf = bins_i32[:, f][:, None]  # (T, 1)
        oh = (binf == iota_b).astype(dtype)  # (T, B)
        h = jax.lax.dot_general(
            pay, oh, (((0,), (0,)), ((), ())),
            preferred_element_type=out_ref.dtype,
        )  # (NC, B)
        out_ref[f] += h


@functools.partial(jax.jit, static_argnames=("num_bins", "row_tile", "matmul_dtype"))
def _hist_pallas_raw(
    bins: jnp.ndarray,  # (N, F) int16/int32
    payload: jnp.ndarray,  # (N, NC) f32 or int8
    *,
    num_bins: int,
    row_tile: int,
    matmul_dtype,
):
    n, f = bins.shape
    nc = payload.shape[1]
    B = _round_up(max(num_bins, 8), 8)
    acc_dtype = jnp.int32 if payload.dtype == jnp.int8 else jnp.float32

    if f > _FEAT_BLOCK:
        # wide data (Epsilon-class): one pallas_call PER 128-feature chunk,
        # unrolled in-trace.  Each call's output/accumulator is (128, NC, B)
        # — small enough that neither the Mosaic ~100MB output ceiling nor
        # scoped VMEM caps the payload lanes, so the leaf tile no longer
        # shrinks with total F (round 2 clamped row_tile to 512 and leaf
        # tile to ~5 at 2000x255; in-trace per-op launches are free, unlike
        # tunnel dispatches)
        outs = [
            _hist_pallas_raw(
                bins[:, j0:j0 + _FEAT_BLOCK], payload,
                num_bins=num_bins, row_tile=row_tile,
                matmul_dtype=matmul_dtype,
            )
            for j0 in range(0, f, _FEAT_BLOCK)
        ]
        return jnp.concatenate(outs, axis=0)

    FB = f  # narrow data: one feature block (wide F recursed above)
    n_pad = _round_up(n, row_tile)
    if n_pad != n:
        bins = jnp.pad(bins, ((0, n_pad - n), (0, 0)))
        payload = jnp.pad(payload, ((0, n_pad - n), (0, 0)))
    grid = (1, n_pad // row_tile)

    out_dims = (f, nc, B)
    out = pl.pallas_call(
        functools.partial(_direct_kernel, FB=FB, B=B, NC=nc, dtype=matmul_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((row_tile, FB), lambda j, i: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile, nc), lambda j, i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((FB, nc, B), lambda j, i: (j, 0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(out_dims, acc_dtype),
        cost_estimate=pl.CostEstimate(
            flops=2 * n_pad * FB * B * nc,
            bytes_accessed=n_pad * FB * bins.dtype.itemsize + n_pad * nc * 4,
            transcendentals=0,
        ),
    )(bins, payload)
    return out


def _split_bf16x2(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """x == hi + lo with both halves exactly representable in bfloat16."""
    hi = x.astype(jnp.bfloat16).astype(jnp.float32)
    return hi, x - hi


def histogram_pallas(
    bins: jnp.ndarray,  # (N, F) int
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    mask: jnp.ndarray,
    num_bins: int,
    *,
    precision: str = "f32",
    row_tile: int = 512,
) -> jnp.ndarray:
    """Masked histogram -> (3, F, B) f32, MXU-accumulated on device.

    precision 'f32' packs bf16x2-split grad/hess into 8 payload lanes (same
    MXU cost as bf16; ~17-bit-mantissa products — see module docstring);
    'bf16' uses rounded payloads in 4 lanes (~8-bit mantissa).
    """
    m = mask.astype(jnp.float32)
    g = grad.astype(jnp.float32) * m
    h = hess.astype(jnp.float32) * m
    if precision == "f32":
        g_hi, g_lo = _split_bf16x2(g)
        h_hi, h_lo = _split_bf16x2(h)
        pay = jnp.stack([g_hi, h_hi, m, jnp.zeros_like(m), g_lo, h_lo,
                         jnp.zeros_like(m), jnp.zeros_like(m)], axis=-1)
    elif precision == "bf16":
        pay = jnp.stack([g, h, m, jnp.zeros_like(m)], axis=-1)
    else:
        raise ValueError(precision)
    out = _hist_pallas_raw(
        bins, pay, num_bins=num_bins, row_tile=row_tile,
        matmul_dtype=jnp.bfloat16,
    )  # (F, NC, B)
    if precision == "f32":
        out3 = jnp.stack(
            [out[:, 0] + out[:, 4], out[:, 1] + out[:, 5], out[:, 2]], axis=0
        )  # (3, F, B)
    else:
        out3 = out[:, :3, :].transpose(1, 0, 2)
    if out3.shape[2] != num_bins:
        out3 = out3[:, :, :num_bins]
    return out3


def histogram_pallas_multi(
    bins: jnp.ndarray,  # (N, F) int
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    mask: jnp.ndarray,  # (N,) in-bag mask
    leaf_id: jnp.ndarray,  # (N,) int32 current leaf per row
    leaf_base: int,
    num_leaves_tile: int,  # histograms for leaves [leaf_base, leaf_base + tile)
    num_bins: int,
    *,
    precision: str = "f32",
    row_tile: int = 1024,
) -> jnp.ndarray:
    """Per-leaf histograms for a tile of leaves in ONE data pass.

    Returns (L_tile, 3, F, B).  Channels are leaf-onehot x payload: lane
    l*NCL + c holds payload channel c masked to leaf leaf_base+l.  With
    NCL=8 (f32 precision) a 128-lane payload covers 16 leaves per pass.
    This is the TPU replacement for per-leaf row-index histogramming
    (reference: Dataset::ConstructHistograms over DataPartition indices).
    """
    m = mask.astype(jnp.float32)
    g = grad.astype(jnp.float32) * m
    h = hess.astype(jnp.float32) * m
    if precision == "f32":
        g_hi, g_lo = _split_bf16x2(g)
        h_hi, h_lo = _split_bf16x2(h)
        chans = [g_hi, h_hi, m, g_lo, h_lo, jnp.zeros_like(m)]
    elif precision == "bf16":
        chans = [g, h, m]
    else:
        raise ValueError(precision)
    ncl = len(chans)
    base = jnp.stack(chans, axis=-1)  # (N, ncl)
    lid = leaf_id.astype(jnp.int32) - leaf_base
    onehot = (
        lid[:, None] == jnp.arange(num_leaves_tile, dtype=jnp.int32)[None, :]
    ).astype(jnp.float32)  # (N, L_tile)
    pay = (onehot[:, :, None] * base[:, None, :]).reshape(
        bins.shape[0], num_leaves_tile * ncl
    )
    nc_pad = _round_up(num_leaves_tile * ncl, 4)
    if nc_pad != pay.shape[1]:
        pay = jnp.pad(pay, ((0, 0), (0, nc_pad - pay.shape[1])))
    out = _hist_pallas_raw(
        bins, pay, num_bins=num_bins, row_tile=row_tile,
        matmul_dtype=jnp.bfloat16,
    )  # (F, nc_pad, B)
    out = out[:, : num_leaves_tile * ncl, :].reshape(
        bins.shape[1], num_leaves_tile, ncl, -1
    )
    if precision == "f32":
        out3 = jnp.stack(
            [out[:, :, 0] + out[:, :, 3], out[:, :, 1] + out[:, :, 4], out[:, :, 2]],
            axis=2,
        )  # (F, L_tile, 3, B)
    else:
        out3 = out[:, :, :3, :]
    out3 = jnp.transpose(out3, (1, 2, 0, 3))  # (L_tile, 3, F, B)
    if out3.shape[3] != num_bins:
        out3 = out3[:, :, :, :num_bins]
    return out3


def quantized_leaf_payload(grad_q, hess_q, mask, leaf_id, leaf_base,
                           num_leaves_tile) -> jnp.ndarray:
    """(N, L_tile*3) int8 payload: leaf-onehot x (grad_q, hess_q, count).
    Shared by the Pallas kernel and the XLA one-hot einsum so the two
    quantized strategies cannot desynchronize."""
    m8 = mask.astype(jnp.int8)
    base = jnp.stack(
        [grad_q.astype(jnp.int8) * m8, hess_q.astype(jnp.int8) * m8, m8],
        axis=-1,
    )  # (N, 3)
    lid = leaf_id.astype(jnp.int32) - leaf_base
    onehot = (
        lid[:, None] == jnp.arange(num_leaves_tile, dtype=jnp.int32)[None, :]
    ).astype(jnp.int8)  # (N, L_tile)
    return (onehot[:, :, None] * base[:, None, :]).reshape(
        grad_q.shape[0], num_leaves_tile * 3
    )


def histogram_pallas_multi_quantized(
    bins: jnp.ndarray,  # (N, F) int
    grad_q: jnp.ndarray,  # (N,) int8 — discretized gradients
    hess_q: jnp.ndarray,  # (N,) int8 — discretized hessians (non-negative)
    mask: jnp.ndarray,  # (N,) in-bag mask
    leaf_id: jnp.ndarray,  # (N,) int32 current leaf per row
    leaf_base: int,
    num_leaves_tile: int,
    num_bins: int,
    *,
    row_tile: int = 1024,
) -> jnp.ndarray:
    """Quantized per-leaf histograms for a tile of leaves in one pass ->
    (L_tile, 3, F, B) int32: exact integer accumulation on the int8 MXU
    (reference: gradient_discretizer.cpp + per-leaf ConstructHistograms).
    Lanes are leaf-onehot x (grad_q, hess_q, count) int8 payload."""
    pay = quantized_leaf_payload(grad_q, hess_q, mask, leaf_id, leaf_base,
                                 num_leaves_tile)
    ncl = 3
    nc_pad = _round_up(num_leaves_tile * ncl, 4)
    if nc_pad != pay.shape[1]:
        pay = jnp.pad(pay, ((0, 0), (0, nc_pad - pay.shape[1])))
    out = _hist_pallas_raw(
        bins, pay, num_bins=num_bins, row_tile=row_tile, matmul_dtype=jnp.int8
    )  # (F, nc_pad, B) int32
    out = out[:, : num_leaves_tile * ncl, :].reshape(
        bins.shape[1], num_leaves_tile, ncl, -1
    )
    out = jnp.transpose(out, (1, 2, 0, 3))  # (L_tile, 3, F, B)
    if out.shape[3] != num_bins:
        out = out[:, :, :, :num_bins]
    return out


def histogram_pallas_quantized(
    bins: jnp.ndarray,
    grad_q: jnp.ndarray,  # (N,) int8 — discretized gradients
    hess_q: jnp.ndarray,  # (N,) int8 — discretized hessians (non-negative)
    mask: jnp.ndarray,
    num_bins: int,
    *,
    row_tile: int = 512,
) -> jnp.ndarray:
    """Quantized histogram -> (3, F, B) int32 (grad_sum, hess_sum, count):
    exact int32 accumulation on the int8 MXU (reference:
    src/treelearner/gradient_discretizer.cpp quantized-training path)."""
    m8 = mask.astype(jnp.int8)
    pay = jnp.stack(
        [grad_q.astype(jnp.int8) * m8, hess_q.astype(jnp.int8) * m8, m8,
         jnp.zeros_like(m8)],
        axis=-1,
    )
    out = _hist_pallas_raw(bins, pay, num_bins=num_bins, row_tile=row_tile,
                           matmul_dtype=jnp.int8)
    out = out[:, :3, :].transpose(1, 0, 2)
    if out.shape[2] != num_bins:
        out = out[:, :, :num_bins]
    return out
