"""Fleet growth — B independent boosters in ONE donated dispatch per round.

The north star serves millions of users, and millions of users don't
share one model: per-tenant personalization means FLEETS of small
ensembles.  Training those as a host loop over ``engine.train`` throws
away everything the fused round bought (1 dispatch / 0 syncs / 0
retraces per round) — B models cost B dispatches per round plus B
python drivers' worth of launch latency, and the chip idles between
them.  This module is the training-side mirror of the multi-tenant
serve table: :func:`jax.vmap` lifts the donated fused round
(ops/treegrow_windowed.py::_round_fused) over a leading model axis so B
boosters — SHARED bin matrix and frozen mappers, PER-MODEL gradients /
hessians / window state / split elections — advance as one donated
jitted dispatch per round.

Protocol.  The existing one-round-behind async driver
(:func:`~.treegrow_windowed._run_fused_rounds`) is reused UNCHANGED:
the (B, 5) per-lane info matrix folds to the driver's 5-scalar vector
inside the same dispatch —

* ``k_acc``  = min over ACTIVE lanes (k > 0), 0 when none remain.  A
  converged lane's round is a bitwise state passthrough with k = 0
  (no admissible split), so lanes that finish early ride as no-op
  lanes and the driver exits only when EVERY lane is done.  Active
  lanes admit >= 1 split per round, so the round count stays bounded
  by the slowest lane's solo schedule (< the driver's 2L+4 guard).
* ``total``  = max (retry re-ladders on the worst lane's need),
* ``ok``     = min (any lane's window breach retries the dispatch),
* ``whint``  = max (the W ladder quantizes on the max live window
  across the batch, so rung changes stay rare and retrace-free),
* ``finite`` = min (any lane going non-finite aborts the fleet —
  the guard names the fleet, the host splits blame by retraining solo).

Bitwise parity.  Each lane's trace is exactly the solo round body —
``jax.vmap`` over ``_round_fused.__wrapped__`` with the shared inputs
unmapped — so per-lane arithmetic is the same op sequence on the same
operands up to the host-side W schedule.  The fleet ladder FLOOR
quantizes on the max live window across the BATCH (per-lane floor
8192/B, 128-quantized; the solo 8192 floor is a per-round compile-cost
bound and a fleet round carries B lanes), so a fleet lane may run a
SMALLER W than its solo run — which is parity-neutral: W padding is row
masking (padded rows contribute exact zeros), each leaf's histogram
accumulates its own rows in row order regardless of how leaves pack
into windows, and admission stays the same best-first split sequence
however it rounds into dispatches.  tests/test_fleet_train.py pins
every lane of a B=64 fleet bitwise against its solo grower run (which
ladders at the 8192 floor), float and int8-quantized.  Mixed-fit
retries are benign the same way: lanes whose window fit already applied
their round (ok folds min, the driver retries without counting k), so a
fitting lane simply advances an uncounted round — admission never
skips.

int8 quantization matches solo bitwise because the stochastic-rounding
key is UNMAPPED under the vmap: every lane draws the same uniforms the
solo grower draws for that (seed, iteration), exactly the solo
semantics where the key depends on config, not data.

Scope (gated loudly here and in models/fleet.py::FleetBooster): the
single-device numerical envelope — no categorical splits, no EFB
bundles, no feature sampling (rng_key), no SPMD axes, no megakernel.
Everything a fleet lane needs beyond that envelope belongs to a solo
``engine.train`` run; jaxlint R18 flags the host-loop anti-pattern the
other direction.

The batched round's IR is pinned by the jaxpr-audit contract
``fleet_round_batched`` (analysis/contracts.py): vmap adds ZERO
collectives vs. the single-model round, donation is consumed on the
(B, ...) state, and peak-live scales linearly in B.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils import degrade as _degrade
from .split import SplitParams
from .treegrow import TreeArrays
from .treegrow_windowed import (_round_fused, _run_fused_rounds, _w_finalize,
                                _w_init, _window_size)

_INT32_MAX = 2 ** 31 - 1


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "num_bins", "max_depth", "params",
                     "leaf_tile", "W", "use_pallas", "quantize_bins",
                     "hist_precision", "pallas_partition"),
    donate_argnums=(0,),  # the (B, ...) window state threads linearly
    # through the host round loop exactly like the solo grower's — donation
    # keeps fleet HBM at one stacked state, not two per round
)
def _fleet_round(
    state,  # WState with every leaf (B, ...)-stacked
    bins_t: jnp.ndarray,  # (F, N) int16 — SHARED, fixed original row order
    grad: jnp.ndarray,  # (B, N) f32 by row id (dequantized under quant)
    hess: jnp.ndarray,  # (B, N)
    gq: Optional[jnp.ndarray],  # (B, N) int8 or None
    hq: Optional[jnp.ndarray],
    quant_scale: Optional[jnp.ndarray],  # (B, 3) or None
    row_mask: jnp.ndarray,  # (B, N) bool — all-False rides as a no-op lane
    num_bins_pf: jnp.ndarray,  # SHARED per-feature tables
    missing_bin_pf: jnp.ndarray,
    feature_mask: jnp.ndarray,
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int,
    params: SplitParams,
    leaf_tile: int,
    W: int,
    use_pallas: bool,
    quantize_bins: int,
    hist_precision: str,
    pallas_partition: bool,
):
    """One boosting round for ALL B lanes: vmapped solo round body plus
    the in-dispatch (B, 5) -> (5,) info fold (module docstring)."""

    def lane(st, g, h, gql, hql, qsl, rm):
        # the UNDECORATED solo body: the inner jit would both ignore its
        # donation under this outer jit and add a trace layer per W; the
        # contracts trace the same .__wrapped__ (analysis/contracts.py)
        return _round_fused.__wrapped__(
            st, bins_t, g, h, gql, hql, qsl, rm,
            num_bins_pf, missing_bin_pf, feature_mask, None, None,
            None, None, None, None,
            num_leaves=num_leaves, num_bins=num_bins, max_depth=max_depth,
            params=params, leaf_tile=leaf_tile, W=W, use_pallas=use_pallas,
            quantize_bins=quantize_bins, hist_precision=hist_precision,
            has_cat=False, pallas_partition=pallas_partition)

    # axis_name-free vmap: zero collectives added vs. the solo round (J1)
    state, info_b = jax.vmap(lane)(state, grad, hess, gq, hq, quant_scale,
                                   row_mask)
    k_b = info_b[:, 0]
    act = k_b > 0
    # min over active lanes; 0 (converged fleet) only when none are active.
    # k=0 lanes are bitwise passthroughs, so min-over-active both bounds
    # the driver's n_leaves accounting from below (the >= num_leaves exit
    # can only fire once EVERY active lane exhausted its budget) and keeps
    # the exit exact: the driver stops exactly when the last lane does.
    k = jnp.where(act.any(),
                  jnp.min(jnp.where(act, k_b, jnp.int32(_INT32_MAX))),
                  jnp.int32(0))
    info = jnp.stack([
        k,
        jnp.max(info_b[:, 1]),  # total: retry ladders on the worst lane
        jnp.min(info_b[:, 2]),  # ok: any breach retries the dispatch
        jnp.max(info_b[:, 3]),  # whint: ladder on the max live window
        jnp.min(info_b[:, 4]),  # finite: any lane's NaN aborts the fleet
    ]).astype(jnp.int32)
    return state, info


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "num_bins", "params", "leaf_tile",
                     "use_pallas", "quantize_bins", "hist_precision",
                     "stochastic_rounding"),
)
def _fleet_init(
    bins_t, grad, hess, row_mask, sample_weight, num_bins_pf,
    missing_bin_pf, feature_mask, quant_key,
    *,
    num_leaves: int,
    num_bins: int,
    params: SplitParams,
    leaf_tile: int,
    use_pallas: bool,
    quantize_bins: int,
    hist_precision: str,
    stochastic_rounding: bool,
):
    """Root state for all B lanes in one dispatch: per-lane quantization
    scales, per-lane full-N root pass, per-lane seeded best.  The
    stochastic-rounding ``quant_key`` is UNMAPPED — every lane draws the
    solo grower's uniforms for this (seed, iteration), which is what the
    bitwise parity bar requires (module docstring)."""

    def lane(g, h, rm, sw):
        return _w_init.__wrapped__(
            bins_t, g, h, rm, sw, num_bins_pf, missing_bin_pf, feature_mask,
            None, quant_key, None, None, None, None, None,
            num_leaves=num_leaves, num_bins=num_bins, params=params,
            leaf_tile=leaf_tile, use_pallas=use_pallas,
            quantize_bins=quantize_bins, hist_precision=hist_precision,
            stochastic_rounding=stochastic_rounding)

    return jax.vmap(lane)(grad, hess, row_mask, sample_weight)


@functools.partial(jax.jit, static_argnames=("params", "quant_renew"))
def _fleet_finalize(state, grad_true, hess_true, row_mask, *,
                    params: SplitParams, quant_renew: bool):
    """Stacked tree extraction: (B, ...) TreeArrays + (B, N) leaf ids."""

    def lane(st, gt, ht, rm):
        return _w_finalize.__wrapped__(st, gt, ht, rm, params=params,
                                       quant_renew=quant_renew)

    return jax.vmap(lane)(state, grad_true, hess_true, row_mask)


def grow_fleet_windowed(
    bins_t: jnp.ndarray,  # (F, N) int16 feature-major — SHARED
    grad: jnp.ndarray,  # (B, N) f32
    hess: jnp.ndarray,  # (B, N) f32
    row_mask: jnp.ndarray,  # (B, N) bool
    sample_weight: jnp.ndarray,  # (B, N) f32
    feature_mask: jnp.ndarray,
    num_bins_pf: jnp.ndarray,
    missing_bin_pf: jnp.ndarray,
    quant_key: Optional[jnp.ndarray] = None,
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    leaf_tile: int = 16,
    hist_precision: str = "f32",
    use_pallas: bool = False,
    quantize_bins: int = 0,
    stochastic_rounding: bool = True,
    quant_renew: bool = False,
    stats: Optional[dict] = None,
    guard_label: str = "",
) -> tuple[TreeArrays, jnp.ndarray]:
    """Grow one tree for EACH of B boosters; one donated dispatch/round.

    Returns ((B, ...)-stacked TreeArrays, (B, N) leaf_id).  ``stats``,
    when given, receives the shared driver's dispatch/sync ledger —
    {rounds, dispatches, host_syncs, async_resolves, retries, windows} —
    which is what the fleet budget pin in tests/test_retrace.py asserts
    at every B.  A lane whose ``row_mask`` is all-False is a no-op lane:
    its root leaf is -0.0 (ops/split.py::leaf_output's KEPSILON
    denominator, never NaN), it admits nothing, and its score update is
    a bitwise identity — device-side early stop, never a host-loop exit.
    """
    if grad.ndim != 2:
        raise ValueError(
            f"fleet: grad must be (B, N), got {grad.shape} — for a single "
            "model use ops.treegrow_windowed.grow_tree_windowed")
    b, n = grad.shape
    if bins_t.ndim != 2 or bins_t.shape[1] != n:
        raise ValueError(
            f"fleet: bins_t must be (F, {n}) shared across lanes, got "
            f"{bins_t.shape}")
    for name, arr in (("hess", hess), ("row_mask", row_mask),
                      ("sample_weight", sample_weight)):
        if arr.shape != (b, n):
            raise ValueError(
                f"fleet: {name} must be {(b, n)}, got {arr.shape}")

    common = dict(num_leaves=num_leaves, num_bins=num_bins, params=params,
                  leaf_tile=leaf_tile)
    state, g_d, h_d, gq, hq, qs, g_true, h_true = _fleet_init(
        bins_t, grad, hess, row_mask, sample_weight, num_bins_pf,
        missing_bin_pf, feature_mask, quant_key,
        use_pallas=use_pallas, quantize_bins=quantize_bins,
        hist_precision=hist_precision,
        stochastic_rounding=stochastic_rounding, **common)

    # same degradation-aware gate as the solo grower: the Pallas segment
    # partition is the TPU default, env/registry drop to the XLA path
    pallas_partition = use_pallas and (
        os.environ.get("LGBMTPU_PARTITION_PALLAS", "1") != "0") and (
        _degrade.available(_degrade.PARTITION))

    def round_fn(st, W):
        st, info = _fleet_round(
            st, bins_t, g_d, h_d, gq, hq, qs, row_mask,
            num_bins_pf, missing_bin_pf, feature_mask,
            max_depth=max_depth, W=W, use_pallas=use_pallas,
            quantize_bins=quantize_bins, hist_precision=hist_precision,
            pallas_partition=pallas_partition, **common)
        return st, info

    # the solo async ladder drives the fleet UNCHANGED — same rungs, same
    # one-round-behind info reads — but the ladder FLOOR quantizes on the
    # max live window ACROSS THE BATCH: the solo 8192 floor is a
    # compile-cost bound per ROUND, and a fleet round carries B lanes, so
    # the per-lane floor shrinks as 8192/B (128-quantized).  W padding is
    # row masking only (padded rows contribute exact zeros), so every
    # lane stays bitwise equal to its solo run at the 8192 floor — pinned
    # in tests/test_fleet_train.py.  Without this, small-N fleets scatter
    # B x 8192 mostly-padding rows per round and the batched dispatch
    # degenerates to the host loop's total compute.
    lane_floor = max(128, (8192 // max(b, 1)) // 128 * 128)
    state = _run_fused_rounds(
        round_fn, state, n_ladder=n,
        w_first=_window_size(max(n // 2, 1), n, lane_floor),
        num_leaves=num_leaves, stats=stats, guard_label=guard_label,
        floor=lane_floor)

    return _fleet_finalize(state, g_true, h_true, row_mask, params=params,
                           quant_renew=bool(quant_renew and quantize_bins))
