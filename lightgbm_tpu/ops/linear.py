"""Per-leaf linear models (linear trees).

Reference: src/treelearner/linear_tree_learner.cpp -> CalculateLinear: after
the tree structure is grown by the constant-leaf method, each leaf gets a
ridge-regularized linear model over the numerical features on its path,
solving (X^T H X + lambda I) beta = -X^T g (the Newton step for the additive
model), with a constant fallback for under-determined leaves and for rows
with NaN in path features.

TPU-first formulation: the reference builds per-leaf normal equations in
scalar loops; here ALL leaves' (K+1)x(K+1) moment matrices are built with
K+1 masked matmuls over the full row set (leaf one-hot x weighted design
rows) and solved as one batched jnp.linalg.solve — fixed shapes, MXU-sized
work, no per-leaf gather lists.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("K", "num_leaves"))
def fit_linear_leaves(
    raw: jnp.ndarray,  # (N, F) f32 raw feature values (NaN allowed)
    leaf_id: jnp.ndarray,  # (N,) i32
    grad: jnp.ndarray,  # (N,) f32
    hess: jnp.ndarray,  # (N,) f32
    row_mask: jnp.ndarray,  # (N,) bool in-bag rows
    used: jnp.ndarray,  # (L, F) bool — features on each leaf's path
    leaf_value: jnp.ndarray,  # (L,) f32 constant leaf outputs (fallback)
    linear_lambda: jnp.ndarray,  # scalar ridge strength
    *,
    K: int,
    num_leaves: int,
):
    """Returns (coef (L,K), const (L,), feat_idx (L,K), nfeat (L,),
    pred (N,) per-row outputs, good (L,) fitted-vs-fallback)."""
    n = raw.shape[0]
    L = num_leaves
    nfeat_full = jnp.sum(used, axis=1).astype(jnp.int32)
    feat_idx = jnp.argsort(~used, axis=1, stable=True)[:, :K].astype(jnp.int32)
    nfeat = jnp.minimum(nfeat_full, K)
    slot_ok = jnp.arange(K, dtype=jnp.int32)[None, :] < nfeat[:, None]  # (L, K)

    ft_rows = feat_idx[leaf_id]  # (N, K)
    ok_rows = slot_ok[leaf_id]
    vals_raw = jnp.take_along_axis(raw, ft_rows, axis=1)  # (N, K)
    finite = jnp.all(jnp.where(ok_rows, jnp.isfinite(vals_raw), True), axis=1)
    vals = jnp.where(ok_rows & jnp.isfinite(vals_raw), vals_raw, 0.0)

    mrow = row_mask & finite
    w = hess * mrow
    z = jnp.concatenate([vals, jnp.ones((n, 1), jnp.float32)], axis=1)  # (N, K+1)
    u = z * jnp.sqrt(jnp.maximum(w, 0.0))[:, None]
    onehot = (
        leaf_id[:, None] == jnp.arange(L, dtype=jnp.int32)[None, :]
    ).astype(jnp.float32)  # (N, L)
    # (L, K+1, K+1) moments via K+1 masked matmuls (see module docstring)
    M = jnp.stack(
        [
            jax.lax.dot_general(
                (onehot * u[:, j:j + 1]), u, (((0,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
            )
            for j in range(K + 1)
        ],
        axis=1,
    )
    gm = grad * mrow
    R = -jax.lax.dot_general(
        onehot * gm[:, None], z, (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )  # (L, K+1)
    lam = linear_lambda + 1e-6
    # padded slots get a unit diagonal so the system stays well-posed and
    # their coefficients are driven to ~0 (then masked exactly)
    pad_diag = jnp.concatenate(
        [(~slot_ok).astype(jnp.float32), jnp.zeros((L, 1), jnp.float32)], axis=1
    )  # (L, K+1)
    A = M + (lam * jnp.eye(K + 1))[None] + jnp.einsum(
        "lk,kj->lkj", pad_diag, jnp.eye(K + 1)
    )
    beta = jnp.linalg.solve(A, R[..., None])[..., 0]  # (L, K+1)
    coef = jnp.where(slot_ok, beta[:, :K], 0.0)
    const = beta[:, K]

    cnt = jnp.sum(onehot * mrow[:, None], axis=0)  # (L,)
    good = (
        (nfeat > 0)
        & jnp.all(jnp.isfinite(beta), axis=1)
        & (cnt > nfeat.astype(jnp.float32) + 1.0)
    )
    coef = jnp.where(good[:, None], coef, 0.0)
    const = jnp.where(good, const, leaf_value)

    pred = const[leaf_id] + jnp.sum(coef[leaf_id] * vals, axis=1)
    pred = jnp.where(finite & good[leaf_id], pred, leaf_value[leaf_id])
    return coef, const, feat_idx, nfeat, pred, good


@jax.jit
def predict_linear_rows(
    raw: jnp.ndarray,  # (N, F)
    leaf_id: jnp.ndarray,  # (N,)
    coef: jnp.ndarray,  # (L, K)
    const: jnp.ndarray,  # (L,)
    feat_idx: jnp.ndarray,  # (L, K)
    nfeat: jnp.ndarray,  # (L,)
    leaf_value: jnp.ndarray,  # (L,) constant fallback (NaN rows)
):
    K = coef.shape[1]
    ft = feat_idx[leaf_id]
    ok = jnp.arange(K, dtype=jnp.int32)[None, :] < nfeat[leaf_id][:, None]
    vals_raw = jnp.take_along_axis(raw, ft, axis=1)
    finite = jnp.all(jnp.where(ok, jnp.isfinite(vals_raw), True), axis=1)
    vals = jnp.where(ok & jnp.isfinite(vals_raw), vals_raw, 0.0)
    pred = const[leaf_id] + jnp.sum(coef[leaf_id] * vals, axis=1)
    return jnp.where(finite, pred, leaf_value[leaf_id])
