"""Windowed round-batched growth — the wide-regime (Epsilon-class) grower.

The round-batched grower (treegrow_fast.py) pays one FULL-N multi-leaf
histogram pass per round: at Epsilon shape (400k x 2000 x 255 bins, 255
leaves) that is ~26 passes x ~200 ms streaming all rows every time, even
though a round only needs histograms for its small children.  This grower
keeps rows PHYSICALLY grouped by leaf (reference: DataPartition's
[start, count) ranges — src/treelearner/data_partition.hpp) so each round
gathers ONLY the small-children rows into a power-of-two window and runs
the pass over that window: total row-touches drop from rounds*N toward
~N (docs/PERF_NOTES.md round-4 plan).

Round 7 structure — ONE donated jit dispatch per round, ZERO blocking
host syncs in steady state.  Rounds 1-6 ran a host loop with two jitted
phases (admit, then pass at a host-chosen static window size W) and one
blocking ``np.asarray`` between them: ~0.10-0.14 s/round of fixed admit
cost, 2 tunnel dispatches and a ~45 ms sync capped the grower at parity
with the full-pass grower (docs/NEXT.md round-6 lever 1).  Now:

* ``_round_fused`` traces admit AND pass in one jitted, donated body.
  The window size W is still jit-static (power-of-two-laddered to bound
  remote Mosaic compiles), but the host no longer syncs to learn it —
  W is PREDICTED, and the round body verifies on device that the real
  window fits (it always does, see the bound below); a breach skips the
  round and reports, so a wrong prediction costs a retried dispatch,
  never a wrong tree.
* the host pipelines 1 round deep: it dispatches round r+1 before
  resolving round r-1's 4-scalar info vector, which was copied back with
  ``copy_to_host_async`` one dispatch earlier — the read overlaps device
  compute of the in-flight round, so the device queue never drains
  (utils/sanitizer.py async_pull_* accounting).
* W prediction: every split's small child holds <= floor(cnt/2) of its
  leaf, and any leaf split within the next TWO rounds descends from a
  leaf live now — two same-parent descendants' small children sum to
  <= floor(parent_cnt/2) — so the sum of the top-(leaf_tile ∧ budget)
  values of floor(leaf_cnt/2) over live leaves bounds BOTH following
  rounds' window totals.  The round body emits that bound (``whint``)
  and the host ladders it two dispatches later: the factor-2 window
  ladder absorbs the slack.
* the row partition inside the fused body goes through
  ops/partition.py::partition_rows: the Pallas segment kernel
  (ops/partition_pallas.py) on TPU — touching only the split segments —
  with the O(N) XLA permutation as the CPU/fallback path.

The per-round dispatch/sync budget is an executable invariant: the
driver counts every dispatch and host pull through utils/sanitizer.py,
``LGBMTPU_DISPATCH_BUDGET=1`` makes it raise on a breach, and
tests/test_retrace.py pins "1 dispatch, 0 blocking syncs per round,
zero retraces" at fixed shape.

Scope (gated in models/gbdt.py): numerical AND (round 5) categorical
splits + EFB bundles; no forced splits / interaction constraints /
monotone constraints / CEGB-lazy — configurations outside this envelope
fall back to the full-pass rounds grower, which supports everything.
Quantized int8 training IS supported (it is the wide-regime TPU
default).

Round 14 (docs/DISTRIBUTED.md "Sharded fused rounds"): the fused round
also runs SPMD over the ICI mesh.  ``_round_fused`` takes an
``axis_name``; under shard_map each rank histograms its local row
shard's window and the leaf-histogram merge is ONE in-dispatch
collective (psum, or psum_scatter + owned-feature split search), with
the 5-scalar info vector collective-merged so the one-round-behind
host protocol stays rank-consistent.  The host loop is shared
(:func:`_run_fused_rounds`); the shard_map plumbing and the SPMD entry
live in parallel/data_parallel.py::grow_tree_windowed_data_parallel.
The 1-dispatch/0-sync budget pin holds PER RANK (single-controller: one
host dispatch fans out over the mesh; tests/test_retrace.py).

Round 20 (docs/DISTRIBUTED.md "Hierarchical merge"): with
``dcn_axis_name`` the round runs the TWO-LEVEL multi-slice merge — the
intra-slice histogram merge above rides ``axis_name`` (the ici axis)
UNCHANGED, the split search goes through the top-k feature election
(parallel/hierarchy.py::dcn_topk_best: slice-local vote, k-feature
histogram exchange, global election — the only histogram-shaped dcn
traffic), and the scalar protocol merges span both axes.  The nested
shard_map plumbing and the SPMD entry live in parallel/hierarchy.py::
grow_tree_windowed_hierarchical.

Round 15: the round executable's IR is ALSO pinned statically — the
jaxpr audit contracts ``windowed_round_float`` / ``_quantized`` /
``_sharded_psum`` / ``_sharded_scatter`` (analysis/contracts.py) trace
:func:`_round_fused` hermetically and verify the exact collective
sequence (one large merge per strategy, declared protocol spine), every
donated WState buffer consumable, and a f64/callback/transfer-free body
under a live-set budget.  Because :func:`_run_fused_rounds` receives the
dispatch as a closure, the AST rules (R1/R6/R13) cannot see into this
body — a change to the collectives or the donation structure here must
update the contract declarations next to their reasoning, or it fails
tests/test_jaxpr_audit.py (docs/ANALYSIS.md "Jaxpr audit layer").
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..obs import metrics as _obs
from ..obs import trace as _trace
from ..utils import degrade as _degrade
from ..utils import sanitizer as _san
from ..utils.guards import NonFiniteError
from .histogram import (histogram, histogram_multi,
                        histogram_multi_quantized, unbundle_hists)
from .partition import partition_rows
from .split import (BestSplit, SplitParams, leaf_output, KMIN_SCORE,
                    select_from_feature_best)
from .treegrow import TreeArrays, _empty_best, _set_best
from .treegrow_fast import _batched_best


class WState(NamedTuple):
    order: jnp.ndarray  # (N,) i32 — row ids physically grouped by leaf
    leaf_start: jnp.ndarray  # (L,) i32 — position of each leaf's range
    leaf_cnt: jnp.ndarray  # (L,) i32
    leaf_id: jnp.ndarray  # (N,) i32 — leaf per ROW (for score updates)
    hist: jnp.ndarray  # (L, 3, F, B) f32 — channel-first (ops/histogram.py)
    best: BestSplit
    leaf_sum_g: jnp.ndarray
    leaf_sum_h: jnp.ndarray
    leaf_count: jnp.ndarray
    leaf_depth: jnp.ndarray
    leaf_parent: jnp.ndarray
    leaf_side: jnp.ndarray
    num_leaves_cur: jnp.ndarray
    leaf_out: jnp.ndarray
    tree: TreeArrays


def _ladder(n: int, floor: int = 8192):
    """The W ladder for (n, floor): factor-4 steps to 128k, then
    factor-2, clamped to (and ending at) round_up(n, floor).  Each
    distinct W is a separate remote Mosaic compile of the fused round
    (1-5 min on this toolchain), so the ladder stays short — but r5
    WPROF showed early rounds with ~130-170k small-children rows landing
    on W=524288 (> N=400k itself!) under pure factor-4, paying 2.5-4x
    window overshoot exactly where passes are biggest.  Ladder for
    N=400k: 8k, 32k, 128k, 256k, 400k-pad (5 sizes)."""
    cap = -(-n // floor) * floor
    w = floor
    while True:
        yield min(w, cap)
        if w >= cap:
            return
        w *= 4 if w < 131072 else 2


def _window_size(x: int, n: int, floor: int = 8192) -> int:
    """Window size quantization: the first ladder rung covering ``x``."""
    for w in _ladder(n, floor):
        if w >= x:
            break
    return w


def _window_rung(w: int, n: int, floor: int = 8192) -> int:
    """Ladder index of window size ``w`` (0 = the floor rung) for the
    same (n, floor) the driver laddered with.  Span attribute only: the
    whint-overshoot question — does the bound climb the ladder earlier
    than the realized windows justify? — is answerable from one trace
    when every ``windowed_round`` span carries its rung and the
    transition that led to it (docs/NEXT.md round-11 queue)."""
    for r, c in enumerate(_ladder(n, floor)):
        if c >= w:
            break
    return r


def _split_tables(axis_name, merge, f_loc, num_bins_pf, missing_bin_pf,
                  feature_mask, categorical_mask, feature_contri,
                  feature_axis_name=None):
    """Per-rank feature tables for the split search.  Replicated (full-F)
    outside the owned-feature merge; when features are OWNED — under
    ``merge="scatter"`` (each rank holds its contiguous F/R block of the
    reduce-scattered histograms) or on a 2-D mesh (each feature-axis
    block holds complete histograms for its F/d_f slice by layout) — the
    rank searches only its block, so the tables are dynamic-sliced at
    this rank's offset along the OWNING axis.  One code path serves both
    ownership sources (reference: the data-parallel learner's per-rank
    feature ownership after ReduceScatter).  Returns the tables plus the
    rank's feature offset (None when features are not owned)."""
    own_axis = (feature_axis_name if feature_axis_name is not None
                else (axis_name if merge == "scatter" else None))
    if own_axis is None:
        return (num_bins_pf, missing_bin_pf, feature_mask, categorical_mask,
                feature_contri, None)
    f0 = jax.lax.axis_index(own_axis) * f_loc

    def sl(v):
        return (None if v is None
                else jax.lax.dynamic_slice_in_dim(v, f0, f_loc, 0))

    return (sl(num_bins_pf), sl(missing_bin_pf), sl(feature_mask),
            sl(categorical_mask), sl(feature_contri), f0)


def _merge_best(bb: BestSplit, axis_name, f0) -> BestSplit:
    """Owned-feature winner election (reference: SyncUpGlobalBestSplit —
    Allreduce of per-rank SplitInfo): globalize each rank's best feature
    index, pmax the gain, tie-break to the lowest-ranked owner (= lowest
    global feature block, matching the replicated argmax), and broadcast
    every winner field from the owner by psum-masking.  All in-dispatch:
    no host-loop collective, no extra dispatch."""
    if axis_name is None or f0 is None:
        return bb
    bb = bb._replace(feature=bb.feature + f0)
    ax_i = jax.lax.axis_index(axis_name)
    gmax = jax.lax.pmax(bb.gain, axis_name)
    cand = jnp.where(bb.gain >= gmax, ax_i, jnp.int32(2 ** 30))
    mine = jax.lax.pmin(cand, axis_name) == ax_i

    def bcast(x):
        m = mine.reshape(mine.shape + (1,) * (x.ndim - 1))
        if x.dtype == bool:
            return jax.lax.psum(
                jnp.where(m, x, False).astype(jnp.int32), axis_name) > 0
        return jax.lax.psum(jnp.where(m, x, jnp.zeros((), x.dtype)),
                            axis_name)

    return BestSplit(*[bcast(x) for x in bb])


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "num_bins", "max_depth", "params",
                     "leaf_tile", "W", "use_pallas", "quantize_bins",
                     "hist_precision", "has_cat", "pallas_partition",
                     "axis_name", "merge", "megakernel", "mk_interpret",
                     "dcn_axis_name", "dcn_top_k", "feature_axis_name"),
    donate_argnums=(0,),  # the 1.5 GB-at-Epsilon hist state threads
    # linearly through the host round loop; donation lets XLA update it in
    # place instead of alloc+copy per call (benchmarks/probe_r5_fixed.py)
)
def _round_fused(
    state: WState,
    bins_t: jnp.ndarray,  # (F, N) int16 — FIXED original row order
    grad: jnp.ndarray,  # (N,) f32 by ROW id (dequantized under quant)
    hess: jnp.ndarray,
    gq: Optional[jnp.ndarray],  # (N,) int8 or None
    hq: Optional[jnp.ndarray],
    quant_scale: Optional[jnp.ndarray],  # (3,) or None
    row_mask: jnp.ndarray,  # (N,) bool by ROW id
    num_bins_pf: jnp.ndarray,
    missing_bin_pf: jnp.ndarray,
    feature_mask: jnp.ndarray,
    rng_key: Optional[jnp.ndarray],
    feature_contri: Optional[jnp.ndarray],
    categorical_mask: Optional[jnp.ndarray] = None,
    efb_bins_t: Optional[jnp.ndarray] = None,  # (F_b, N) bundled matrix
    efb_gather: Optional[jnp.ndarray] = None,  # (F, B) -> flat (F_b*B)+pad
    efb_default: Optional[jnp.ndarray] = None,  # (F, B) bool default slots
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int,
    params: SplitParams,
    leaf_tile: int,
    W: int,
    use_pallas: bool,
    quantize_bins: int,
    hist_precision: str,
    has_cat: bool = False,
    pallas_partition: bool = False,
    axis_name: Optional[str] = None,
    merge: str = "psum",
    megakernel: bool = False,
    mk_interpret: bool = False,
    dcn_axis_name: Optional[str] = None,
    dcn_top_k: int = 0,
    feature_axis_name: Optional[str] = None,
):
    """One whole boosting round in one traced body: gain admission,
    segment partition, bookkeeping, window gather, multi-leaf pass,
    sibling subtraction, fresh-leaf search, next-window bound.

    Returns (state', info) with info = [k_acc, window_total, fits_W,
    whint, finite] (i32) — the ONLY values that ever reach the host, read
    asynchronously one round behind.  If the admitted splits' window
    would not fit the static W (impossible while the whint bound holds;
    kept as a device-verified safety net), the round applies NOTHING
    (bitwise-identical state passthrough) and reports fits_W=0 with the
    needed total so the host retries at a corrected W.

    With ``axis_name`` the body runs SPMD under shard_map over the mesh
    data axis (docs/DISTRIBUTED.md "Sharded fused rounds"): rows (and
    every row-indexed input) are this RANK's shard, the leaf-histogram
    merge is a single in-dispatch collective — ``psum`` with
    ``merge="psum"`` (replicated histograms, replicated split search) or
    ``psum_scatter`` with ``merge="scatter"`` (owned-feature split search
    + winner election, the ReduceScatter analogue) — and the 5-scalar
    info vector is collective-merged so every rank's host ladder sees
    identical values.  Physical row bookkeeping (order, leaf ranges,
    partition) stays rank-local; split decisions and tree arrays are
    replicated.

    With ``dcn_axis_name`` the body runs the TWO-LEVEL hierarchical merge
    (docs/DISTRIBUTED.md "Hierarchical merge"): ``axis_name`` is the
    intra-slice ICI axis — the histogram merge above runs UNCHANGED
    there, per slice — and the split search crosses slices DCN-frugally:
    each slice elects its ``dcn_top_k`` best features per candidate
    locally, only those k features' histograms + gain scalars travel the
    ``dcn`` axis (parallel/hierarchy.py::dcn_topk_best), and a global
    election picks the winner.  ``state.hist`` then holds SLICE-domain
    histograms (sibling subtraction works per slice), the scalar
    protocol merges (window election, info vector) span BOTH axes, and
    NO full-F histogram ever crosses DCN — pinned statically by jaxlint
    R17 and the jaxpr-audit ``dcn_max_bytes`` contract pin.

    With ``feature_axis_name`` the body runs over a 2-D (feature, row)
    mesh (docs/DISTRIBUTED.md "2-D sharding"): ``bins_t`` is this rank's
    (F/d_f, N/d_r) tile, rows and every row-indexed input are the ROW
    shard (replicated across the feature axis), and the per-leaf window
    histograms are COMPLETE for the owned feature block by layout — the
    histogram merge stays the row-axis collective alone, with ZERO
    collective over the feature axis (pinned by jaxlint R20 and the
    ``windowed_round_2d_*`` jaxpr contracts).  The split search reuses
    the scatter merge's owned-feature machinery (``_split_tables`` /
    ``_merge_best``) with the feature axis as the owning axis, and the
    winner's split decisions — computable only on the owner block, which
    alone holds the winner feature's bin column — are psum-broadcast
    over the feature axis (a (N,)-bool vector, the only feature-axis
    exchange in the round).  Row-domain sums stay on the row axes alone:
    rows are REPLICATED across the feature axis, so summing there would
    over-count by d_f.
    """
    L = num_leaves
    f = bins_t.shape[0]
    n = state.order.shape[0]
    # axis discipline: `sum_axes` are the ROW-sharding axes — row-domain
    # sums (window counts, leaf totals) merge there and ONLY there (rows
    # are replicated across the feature axis; summing there would
    # over-count by the feature-axis size).  `all_axes` adds the feature
    # axis for the IDEMPOTENT protocol merges (pmin/pmax agreement on
    # ok/total/whint/finite): under the two-level merge, window-child
    # election and the info vector are GLOBAL agreements (all slices, all
    # ranks, all feature blocks) while the histogram merge stays
    # per-slice on axis_name alone
    sum_axes = tuple(a for a in (axis_name, dcn_axis_name) if a is not None)
    all_axes = sum_axes + (
        (feature_axis_name,) if feature_axis_name is not None else ())

    def pall(x):  # cross-rank ROW-domain sum; identity on 1 device
        return jax.lax.psum(x, sum_axes) if sum_axes else x
    eps = KMIN_SCORE / 2
    idx = jnp.arange(L, dtype=jnp.int32)
    pos = jnp.arange(n, dtype=jnp.int32)

    # ---- admission (identical semantics to treegrow_fast round_body) ----
    gains = state.best.gain
    can = gains > eps
    if max_depth > 0:
        can = can & (state.leaf_depth < max_depth)
    budget = L - state.num_leaves_cur
    key = jnp.where(can, -gains, jnp.inf)
    srt = jnp.argsort(key)  # leaf at rank r (stable); doubles as inv_rank
    order_rank = jnp.argsort(srt)
    accept0 = can & (order_rank < jnp.minimum(budget, leaf_tile))
    s = state.best

    # ---- split decisions + segment geometry (pre-partition) ----
    # One fused gather instead of leaf_tile full-N column gathers (measured
    # ~240 ms/round the sequential way at 400k x 2000): slice the <= tile
    # accepted split features into a (tile, N) block (contiguous row reads
    # of bins_t), gather the row order ONCE along the position axis, and
    # select each position's own segment's row with an elementwise one-hot.
    seg_id = jnp.full((n,), -1, jnp.int32)
    seg_start = jnp.zeros((leaf_tile,), jnp.int32)
    seg_len = jnp.zeros((leaf_tile,), jnp.int32)
    ord_rows = state.order
    leaf_of_rank = srt[:leaf_tile]
    live_rk = accept0[leaf_of_rank]
    feats_rk = jnp.where(live_rk, s.feature[leaf_of_rank], 0)
    if feature_axis_name is not None:
        # 2-D mesh: bins_t holds only this rank's owned feature block, so
        # a winner column exists on exactly ONE feature block (feats_rk
        # are GLOBAL indices < f*d_f — every value has one owner).  Gather
        # the clipped local column here; the owner's decisions are
        # psum-broadcast over the feature axis after go_left is complete.
        f0_dec = jax.lax.axis_index(feature_axis_name) * f
        feats_loc = feats_rk - f0_dec
        own_rk = (feats_loc >= 0) & (feats_loc < f)
        cols = bins_t[jnp.clip(feats_loc, 0, f - 1)]  # (tile, N) by ROW id
    else:
        own_rk = None
        cols = bins_t[feats_rk]  # (tile, N) by ROW id
    colv = cols[:, ord_rows].astype(jnp.int32)  # (tile, N) by POSITION
    for r in range(leaf_tile):
        leaf_r = leaf_of_rank[r]
        live_r = live_rk[r]
        st, ct = state.leaf_start[leaf_r], state.leaf_cnt[leaf_r]
        seg_start = seg_start.at[r].set(jnp.where(live_r, st, 0))
        seg_len = seg_len.at[r].set(jnp.where(live_r, ct, 0))
        in_seg = live_r & (pos >= st) & (pos < st + ct)
        seg_id = jnp.where(in_seg, r, seg_id)
    sid = jnp.clip(seg_id, 0, leaf_tile - 1)
    oh = (jnp.arange(leaf_tile, dtype=jnp.int32)[:, None] == sid[None, :])
    # per-rank split scalars broadcast through the same one-hot — keeps
    # every (N,)-shaped op elementwise (no small-table row gathers)
    thr_rk = s.threshold_bin[leaf_of_rank][:, None]
    dl_rk = s.default_left[leaf_of_rank][:, None]
    mb_rk = missing_bin_pf[feats_rk][:, None]
    vals = jnp.sum(jnp.where(oh, colv, 0), axis=0)
    thr = jnp.sum(jnp.where(oh, thr_rk, 0), axis=0)
    mb = jnp.sum(jnp.where(oh, mb_rk, -1), axis=0) + (leaf_tile - 1)
    dl = jnp.any(oh & dl_rk, axis=0)
    go_left = jnp.where(vals == mb, dl, vals <= thr)
    if has_cat:
        # categorical winners route by bitset membership (reference:
        # Tree::CategoricalDecision — not-in-subset, incl. missing, goes
        # right); same per-rank one-hot select as the numeric scalars
        cat_rk = s.is_cat[leaf_of_rank][:, None]  # (tile, 1)
        cmask_rk = s.cat_mask[leaf_of_rank]  # (tile, B)
        go_cat_rk = jnp.take_along_axis(cmask_rk, colv, axis=1)  # (tile, N)
        in_cat = jnp.any(oh & cat_rk, axis=0)
        gc = jnp.any(oh & go_cat_rk, axis=0)
        go_left = jnp.where(in_cat, gc, go_left)
    if feature_axis_name is not None:
        # broadcast each position's decision from its segment's OWNER
        # feature block — the only block whose go_left gathered the real
        # winner column.  Exactly one block owns each slot's feature, so
        # the psum is a pure select; positions outside every live segment
        # take slot 0's value and are masked downstream (seg_id < 0).
        # This (N,)-bool vector is the round's ONLY feature-axis data
        # exchange — the histogram phase stays @feature-collective-free.
        own_pos = jnp.any(oh & own_rk[:, None], axis=0)
        go_left = jax.lax.psum(
            jnp.where(own_pos, go_left, False).astype(jnp.int32),
            feature_axis_name) > 0

    # ---- on-device window verification (the fused round's safety net) ----
    # per-slot left counts from the one-hot the decisions already built —
    # O(tile*N) elementwise, no extra cumsums; in-segment positions only
    in_seg_all = seg_id >= 0
    left_counts = jnp.sum(
        (oh & (go_left & in_seg_all)[None, :]).astype(jnp.int32), axis=1)
    # which child gets histogrammed directly must be the GLOBALLY smaller
    # one: under SPMD every rank contributes its local window rows to one
    # collective-merged histogram, so ranks must agree on the side even
    # when their local row splits disagree (single-device: pall is the
    # identity and this is exactly min(left, count-left))
    left_small = 2 * pall(left_counts) <= pall(seg_len)  # (tile,)
    win_cnt_rk = jnp.where(
        live_rk,
        jnp.where(left_small, left_counts, seg_len - left_counts), 0)
    total = jnp.sum(win_cnt_rk)  # LOCAL rows this rank must window
    ok = total <= W  # guaranteed by the whint bound; verified anyway
    if all_axes:
        # one rank breaching skips the round EVERYWHERE (the no-op must be
        # fleet-consistent), and the host's corrected W must cover the
        # worst rank — merged here so the async info vector is replicated
        ok = jax.lax.pmin(ok.astype(jnp.int32), all_axes) > 0
        total = jax.lax.pmax(total, all_axes)

    # everything applied below is gated on `ok`: a breached prediction
    # makes the whole round a bitwise no-op (state threads through
    # unchanged) and the host folds the correction into the next dispatch
    accept = accept0 & ok
    live_rk = live_rk & ok
    k_acc = jnp.sum(accept.astype(jnp.int32))
    acc_rank = jnp.where(accept, order_rank, L)
    node_of = state.num_leaves_cur - 1 + acc_rank
    right_of = state.num_leaves_cur + acc_rank
    seg_id = jnp.where(ok, seg_id, -1)
    seg_len_eff = jnp.where(ok, seg_len, 0)
    n_left_seg = jnp.where(live_rk, left_counts, 0)

    # ---- order-independent bookkeeping (leaf stats, slot maps, leaf
    # ranges, this round's windows), hoisted AHEAD of the partition: the
    # megakernel consumes the window geometry and — single-device — the
    # candidate stats inside the SAME kernel that partitions the rows.
    # Pure statement reordering for the legacy path (same value graph).
    right_pos = jnp.where(accept, right_of, 2 * L)

    def upd(arr, left_val, right_val):
        arr = jnp.where(accept, left_val, arr)
        return arr.at[right_pos].set(right_val, mode="drop")

    leaf_sum_g = upd(state.leaf_sum_g, s.left_sum_g, s.right_sum_g)
    leaf_sum_h = upd(state.leaf_sum_h, s.left_sum_h, s.right_sum_h)
    leaf_count = upd(state.leaf_count, s.left_count, s.right_count)
    depth_child = state.leaf_depth + 1
    leaf_depth = jnp.where(accept, depth_child, state.leaf_depth)
    leaf_depth = leaf_depth.at[right_pos].set(depth_child, mode="drop")
    leaf_parent = jnp.where(accept, node_of, state.leaf_parent)
    leaf_parent = leaf_parent.at[right_pos].set(
        jnp.where(accept, node_of, 0), mode="drop")
    leaf_side = jnp.where(accept, 0, state.leaf_side)
    leaf_side = leaf_side.at[right_pos].set(1, mode="drop")
    out_l = leaf_output(s.left_sum_g, s.left_sum_h, params)
    out_r = leaf_output(s.right_sum_g, s.right_sum_h, params)
    leaf_out = jnp.where(accept, out_l, state.leaf_out)
    leaf_out = leaf_out.at[right_pos].set(out_r, mode="drop")
    num_leaves_new = state.num_leaves_cur + k_acc

    # per-slot child maps stay LOCAL to the fused body (rounds 1-6 carried
    # them in WState to hand admit's result to the separate pass dispatch;
    # the fusion is what lets them die here).
    # The window child is chosen by PHYSICAL row counts — the same
    # quantity the gather pays for, the `ok` check verified against W,
    # and the whint bound promises about (rounds 1-6 chose by in-bag
    # counts, which under bagging can pick the physically BIGGER child
    # and desynchronize the window sum from the verified total; which
    # child is histogrammed directly vs recovered by subtraction does
    # not change the children's histograms).  Under SPMD the choice is
    # by GLOBAL counts (left_small above) so every rank windows the same
    # child and the collective merge sums one child's rows.
    left_smaller_rk = left_small  # (tile,) per slot, rank-consistent
    fresh = jnp.where(accept, True, jnp.zeros((L,), bool))
    fresh = fresh.at[right_pos].set(True, mode="drop")
    pos_r = jnp.where(accept, acc_rank, leaf_tile)
    slot_left = jnp.full((leaf_tile,), -1, jnp.int32).at[pos_r].set(
        idx, mode="drop")
    slot_right = jnp.full((leaf_tile,), -1, jnp.int32).at[pos_r].set(
        right_of, mode="drop")
    slot_small_left = live_rk & left_smaller_rk  # slot r == rank r

    # leaf ranges (the order-independent half of the range bookkeeping;
    # the per-row leaf ids need the partitioned order and follow it)
    leaf_start, leaf_cnt = state.leaf_start, state.leaf_cnt
    for r in range(leaf_tile):
        leaf_r = srt[r]
        live_r = accept[leaf_r]
        st, ct = state.leaf_start[leaf_r], state.leaf_cnt[leaf_r]
        lc = n_left_seg[r]
        rp = jnp.clip(right_of[leaf_r], 0, L - 1)
        leaf_start = jnp.where(
            live_r, leaf_start.at[rp].set(st + lc), leaf_start)
        leaf_cnt = jnp.where(
            live_r, leaf_cnt.at[leaf_r].set(lc).at[rp].set(ct - lc), leaf_cnt)

    # windows: per admission rank, the SMALL child's [start, cnt)
    win_start = jnp.zeros((leaf_tile,), jnp.int32)
    win_cnt = jnp.zeros((leaf_tile,), jnp.int32)
    for r in range(leaf_tile):
        leaf_r = srt[r]
        live_r = accept[leaf_r]
        sm = jnp.where(left_smaller_rk[r], leaf_r,
                       jnp.clip(right_of[leaf_r], 0, L - 1))
        win_start = win_start.at[r].set(jnp.where(live_r, leaf_start[sm], 0))
        win_cnt = win_cnt.at[r].set(jnp.where(live_r, leaf_cnt[sm], 0))

    # candidate slot maps (shared by the sibling recovery below and the
    # megakernel's fused tail)
    active = slot_left >= 0  # (tile,)
    sl = jnp.clip(slot_left, 0, L - 1)
    sr = jnp.clip(slot_right, 0, L - 1)
    parent_hists = state.hist[sl]  # (tile, 3, F, B)
    cand = jnp.concatenate([sl, sr])
    cand_ok = jnp.concatenate([active, active])
    ci = jnp.where(cand_ok, cand, 0)

    # ---- partition the physical row order at segment boundaries ----
    mk_tail = megakernel and axis_name is None
    if megakernel:
        # THE round megakernel (ops/round_pallas.py): partition movements,
        # the one-sweep window histogram, and (single-device) the on-core
        # split-gain reduction, all in ONE Pallas call.  Same raw-order
        # contract as the partition kernel: merge untouched positions
        # back.  Under SPMD the kernel stops after the histograms so the
        # single in-dispatch collective merge below stays UNCHANGED.
        from .round_pallas import round_megakernel

        if efb_bins_t is not None or rng_key is not None:
            raise ValueError(
                "megakernel round outside its envelope (EFB bundles / "
                "per-node rng) — the entry gate must fall back to the "
                "three-pass round")
        cand_tab = (jnp.stack([
            leaf_sum_g[ci], leaf_sum_h[ci], leaf_count[ci],
            leaf_depth[ci].astype(jnp.float32), leaf_out[ci]])
            if mk_tail else None)
        mk_out = round_megakernel(
            bins_t, ord_rows, go_left, grad, hess, row_mask,
            seg_start, seg_len_eff, n_left_seg, win_start, win_cnt,
            slot_small_left.astype(jnp.int32),
            parent_hists if mk_tail else None,
            cand_tab,
            num_bins_pf if mk_tail else None,
            missing_bin_pf if mk_tail else None,
            feature_mask if mk_tail else None,
            categorical_mask if mk_tail else None,
            feature_contri if mk_tail else None,
            num_bins=num_bins, leaf_tile=leaf_tile, params=params,
            fuse_tail=mk_tail, has_cat=has_cat, interpret=mk_interpret)
        new_order = jnp.where(seg_id >= 0, mk_out[0], ord_rows)
    else:
        mk_out = None
        new_order, _ = partition_rows(
            ord_rows, seg_id, seg_start, seg_len_eff, go_left,
            use_pallas=pallas_partition)

    # ---- per-row leaf ids (needs the partitioned order) ----
    lid_pos = state.leaf_id[new_order]  # leaf per POSITION (pre-split)
    for r in range(leaf_tile):
        leaf_r = srt[r]
        live_r = accept[leaf_r]
        st, ct = state.leaf_start[leaf_r], state.leaf_cnt[leaf_r]
        lc = n_left_seg[r]
        in_right = live_r & (pos >= st + lc) & (pos < st + ct)
        lid_pos = jnp.where(in_right, right_of[leaf_r], lid_pos)
    leaf_id = jnp.zeros_like(state.leaf_id).at[new_order].set(lid_pos)

    # ---- tree arrays (identical bookkeeping to round_body) ----
    t = state.tree
    parent_out = state.leaf_out
    old_parent, old_side = state.leaf_parent, state.leaf_side
    repoint_l = accept & (old_parent >= 0) & (old_side == 0)
    repoint_r = accept & (old_parent >= 0) & (old_side == 1)
    safe_node = jnp.clip(node_of, 0, L - 2)
    lc_t = t.left_child.at[jnp.where(repoint_l, old_parent, 2 * L)].set(
        safe_node, mode="drop")
    rc_t = t.right_child.at[jnp.where(repoint_r, old_parent, 2 * L)].set(
        safe_node, mode="drop")
    node_pos = jnp.where(accept, node_of, 2 * L)
    lc_t = lc_t.at[node_pos].set(-idx - 1, mode="drop")
    rc_t = rc_t.at[node_pos].set(-right_of - 1, mode="drop")
    tree = t._replace(
        num_leaves=state.num_leaves_cur + k_acc,
        split_feature=t.split_feature.at[node_pos].set(s.feature, mode="drop"),
        threshold_bin=t.threshold_bin.at[node_pos].set(s.threshold_bin, mode="drop"),
        default_left=t.default_left.at[node_pos].set(s.default_left, mode="drop"),
        split_gain=t.split_gain.at[node_pos].set(s.gain, mode="drop"),
        left_child=lc_t,
        right_child=rc_t,
        internal_value=t.internal_value.at[node_pos].set(parent_out, mode="drop"),
        internal_weight=t.internal_weight.at[node_pos].set(state.leaf_sum_h, mode="drop"),
        internal_count=t.internal_count.at[node_pos].set(state.leaf_count, mode="drop"),
        is_cat=t.is_cat.at[node_pos].set(s.is_cat, mode="drop"),
        cat_mask=t.cat_mask.at[node_pos].set(s.cat_mask, mode="drop"),
    )

    best = state.best._replace(
        gain=jnp.where(fresh, jnp.full((L,), KMIN_SCORE, jnp.float32),
                       state.best.gain))

    # ---- pass: window histograms -> sibling subtraction -> fresh-leaf
    # split search (same trace, no dispatch).  Three sources for the
    # child histograms: the megakernel's fused tail (everything already
    # computed in-kernel), the megakernel's histogram-only output (the
    # SPMD case: the collective merge below must stay the round's single
    # large in-dispatch collective), or the legacy gather + multi-leaf
    # pass (three bin sweeps — docs/PERF_NOTES.md round 16).
    mk_bests = None
    if megakernel and mk_tail:
        _, left_hists, right_hists, mk_bests = mk_out
    else:
        if megakernel:
            fresh_hists = mk_out[1]
        else:
            offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                    jnp.cumsum(win_cnt).astype(jnp.int32)])
            w_total = offs[-1]
            aw = jnp.arange(W, dtype=jnp.int32)
            # slot per window element: number of boundaries <= position
            slot_of = jnp.sum(
                (aw[:, None] >= offs[1:][None, :]).astype(jnp.int32), axis=1)
            slot_of = jnp.clip(slot_of, 0, leaf_tile - 1)
            wpos = win_start[slot_of] + (aw - offs[slot_of])
            valid = aw < w_total
            wpos = jnp.where(valid, wpos, 0)
            rows = new_order[wpos]  # (W,) row ids

            # feature-major window gather (a row gather on the (N, F)
            # layout measured ~909 ms at 1M x 28; column slices of (F, N)
            # are ~20x cheaper), then ONE contiguous transpose for the
            # row-major kernel — a lane->sublane reshape per feature
            # inside a feature-major kernel blew the 16M scoped-VMEM
            # budget (measured 19.6M)
            hist_src = bins_t if efb_bins_t is None else efb_bins_t
            sub_bins = hist_src[:, rows].T  # (W, F) or (W, F_b)
            mask_w = row_mask[rows] & valid

            def unbundle(h):
                if efb_gather is None:
                    return h
                return unbundle_hists(h, efb_gather, efb_default, f,
                                      num_bins)

            if quantize_bins and use_pallas:
                hi = histogram_multi_quantized(
                    sub_bins, gq[rows], hq[rows], mask_w, slot_of, 0,
                    leaf_tile, num_bins)
                fresh_hists = unbundle(hi).astype(
                    jnp.float32) * quant_scale[:, None, None]
            elif use_pallas:
                fresh_hists = unbundle(histogram_multi(
                    sub_bins, grad[rows], hess[rows], mask_w, slot_of, 0,
                    leaf_tile, num_bins, precision=hist_precision))
            else:
                # CPU/test fallback: masked scatter per slot over the window
                g_w, h_w = grad[rows], hess[rows]

                def one(sl_):
                    m = (mask_w & (slot_of == sl_)).astype(jnp.float32)
                    return histogram(sub_bins, g_w, h_w, m, num_bins,
                                     strategy="scatter")
                fresh_hists = unbundle(
                    jax.vmap(one)(jnp.arange(leaf_tile, dtype=jnp.int32)))

        # ---- in-dispatch cross-rank histogram merge ----
        # each rank histogrammed ONLY its local shard of the window; the
        # merge is one collective INSIDE the already-donated dispatch — no
        # host-loop collective, no second dispatch (reference:
        # DataParallelTreeLearner's per-split ReduceScatter, paid here
        # once per ROUND).  "psum" leaves every rank with the global
        # (tile, 3, F, B) block; "scatter" leaves each rank the global
        # block for its OWNED F/R feature slice only (half the merge
        # bytes, split search parallelized over F).  The megakernel path
        # feeds its local histograms through this SAME merge unchanged.
        if axis_name is not None:
            if merge == "scatter":
                fresh_hists = jax.lax.psum_scatter(
                    fresh_hists, axis_name, scatter_dimension=2, tiled=True)
            else:
                fresh_hists = jax.lax.psum(fresh_hists, axis_name)

        # COMPACT sibling recovery (round 5, mirrors treegrow_fast):
        # gather the <= tile parent hists from the left-child slots,
        # subtract, scatter both children once — O(tile) state traffic
        big_hists = parent_hists - fresh_hists
        sml = slot_small_left[:, None, None, None]
        left_hists = jnp.where(sml, fresh_hists, big_hists)
        right_hists = jnp.where(sml, big_hists, fresh_hists)

    lpos = jnp.where(active, sl, 2 * L)
    rpos = jnp.where(active, sr, 2 * L)
    hist = state.hist.at[lpos].set(left_hists, mode="drop").at[rpos].set(
        right_hists, mode="drop")

    # fresh-leaf split search directly on the compact child hists; under
    # merge="scatter" each rank searches its owned feature block and the
    # winner is elected + broadcast in-dispatch (_merge_best).  With the
    # megakernel tail the per-feature reduction already happened ON-CORE
    # (ops/split.py::reduce_plane_per_feature inside the kernel); only
    # the O(F) cross-feature selection runs here.
    node_ids = jnp.clip(leaf_parent, 0, None) * 2 + leaf_side + 1
    cand_hists = jnp.concatenate([left_hists, right_hists], axis=0)
    if mk_bests is not None:
        def _sel(fbx, ch, pg, ph, pc):
            return select_from_feature_best(
                fbx, pg, ph, pc, categorical_mask=categorical_mask,
                cand_hist=ch, missing_bin_per_feature=missing_bin_pf,
                params=params, num_bins=num_bins)

        bb = jax.vmap(_sel)(mk_bests, cand_hists, leaf_sum_g[ci],
                            leaf_sum_h[ci], leaf_count[ci])
    else:
        nb_l, mb_l, fm_l, cm_l, fc_l, f0 = _split_tables(
            axis_name, merge, state.hist.shape[2], num_bins_pf,
            missing_bin_pf, feature_mask, categorical_mask, feature_contri,
            feature_axis_name=feature_axis_name)
        if dcn_axis_name is not None:
            # two-level split search (parallel/hierarchy.py): the cand
            # hists above are SLICE-domain (merged over axis_name only);
            # each slice votes its top-k features per candidate, only k
            # features' histograms + gain scalars cross the dcn axis, and
            # the winner is elected on the k-feature GLOBAL histograms —
            # the PV-Tree/voting-parallel route, in-dispatch
            from ..parallel.hierarchy import dcn_topk_best

            bb = dcn_topk_best(
                cand_hists, leaf_sum_g[ci], leaf_sum_h[ci], leaf_count[ci],
                nb_l, mb_l, fm_l, cm_l, fc_l,
                params=params, top_k=dcn_top_k, dcn_axis=dcn_axis_name,
                depth=leaf_depth[ci], parent_out=leaf_out[ci])
        else:
            bb = _batched_best(
                cand_hists, leaf_sum_g[ci], leaf_sum_h[ci],
                leaf_count[ci], nb_l, mb_l, params,
                fm_l, cm_l, None, None,
                jnp.full((2 * leaf_tile,), -jnp.inf, jnp.float32),
                jnp.full((2 * leaf_tile,), jnp.inf, jnp.float32),
                None, node_ids[ci], rng_key,
                depth=leaf_depth[ci], parent_out=leaf_out[ci],
                feature_contri=fc_l,
            )
        bb = _merge_best(
            bb, feature_axis_name if feature_axis_name is not None
            else axis_name, f0)
    scatter_pos = jnp.where(cand_ok, cand, 2 * L)

    def merge(old, new):
        return old.at[scatter_pos].set(new, mode="drop")

    best = BestSplit(*[merge(o, nw) for o, nw in zip(best, bb)])

    # ---- next-window bound for the host's ladder prediction ----
    # any leaf split within the next two rounds descends from a leaf live
    # NOW; the small children under one live ancestor sum to
    # <= floor(ancestor_cnt/2), and distinct split leaves have distinct
    # live ancestors — so the top-(tile ∧ budget) floor(cnt/2) over live
    # leaves bounds both following window totals.  Exact enough that the
    # factor-2 ladder absorbs the slack; always an over- (never under-)
    # estimate, so the on-device `ok` check cannot trip while the host
    # ladders this value.
    #
    # SPMD variant: the halving argument is GLOBAL (the window child is
    # the globally smaller one), but W bounds each rank's LOCAL window —
    # and a globally-small child can hold up to ALL of one rank's rows of
    # its ancestor.  The sound local bound drops the halving: top-(tile ∧
    # budget) local leaf_cnt over live leaves covers both following
    # rounds (window children under one live ancestor are disjoint row
    # subsets of it).  pmax makes the laddered W cover the worst rank.
    live_next = idx < num_leaves_new
    half_cnt = jnp.where(
        live_next, leaf_cnt // 2 if axis_name is None else leaf_cnt, 0)
    k_top = min(leaf_tile, L)
    top_halves = jax.lax.top_k(half_cnt, k_top)[0]
    budget_next = jnp.maximum(L - num_leaves_new, 0)
    whint = jnp.sum(jnp.where(
        jnp.arange(k_top, dtype=jnp.int32) < jnp.minimum(
            budget_next, leaf_tile),
        top_halves, 0))
    if all_axes:
        whint = jax.lax.pmax(whint, all_axes)

    state = WState(
        order=new_order, leaf_start=leaf_start, leaf_cnt=leaf_cnt,
        leaf_id=leaf_id, hist=hist, best=best,
        leaf_sum_g=leaf_sum_g, leaf_sum_h=leaf_sum_h, leaf_count=leaf_count,
        leaf_depth=leaf_depth, leaf_parent=leaf_parent, leaf_side=leaf_side,
        num_leaves_cur=num_leaves_new, leaf_out=leaf_out, tree=tree,
    )
    # ---- non-finite guard rail (docs/ROBUSTNESS.md layer 2) ----
    # O(L) reductions over stats this round already produced, folded into
    # the SAME info vector the host reads one round behind: the guard
    # costs zero extra dispatches and zero blocking syncs.  Dead slots
    # hold zeros / KMIN, so any non-finite value is corruption that
    # entered through the gradients/hessians or split accumulation.
    finite = (jnp.isfinite(leaf_sum_g).all()
              & jnp.isfinite(leaf_sum_h).all()
              & jnp.isfinite(leaf_out).all()
              & ~jnp.isnan(best.gain).any())
    if all_axes:
        # replicated by construction (split stats come from the merged
        # histograms), but pmin pins rank consistency as an invariant —
        # the host's one-round-behind guard must never see ranks disagree
        finite = jax.lax.pmin(finite.astype(jnp.int32), all_axes) > 0
    info = jnp.stack([
        k_acc, total, ok.astype(jnp.int32), whint.astype(jnp.int32),
        finite.astype(jnp.int32),
    ]).astype(jnp.int32)
    return state, info


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "num_bins", "params", "leaf_tile",
                     "use_pallas", "quantize_bins", "hist_precision",
                     "stochastic_rounding", "axis_name", "merge",
                     "dcn_axis_name", "dcn_top_k", "feature_axis_name"),
)
def _w_init(
    bins_t, grad, hess, row_mask, sample_weight, num_bins_pf,
    missing_bin_pf, feature_mask, rng_key, quant_key, feature_contri,
    categorical_mask=None, efb_bins_t=None, efb_gather=None,
    efb_default=None,
    *,
    num_leaves: int,
    num_bins: int,
    params: SplitParams,
    leaf_tile: int,
    use_pallas: bool,
    quantize_bins: int,
    hist_precision: str,
    stochastic_rounding: bool,
    axis_name: Optional[str] = None,
    merge: str = "psum",
    dcn_axis_name: Optional[str] = None,
    dcn_top_k: int = 0,
    feature_axis_name: Optional[str] = None,
):
    """Root state: quantize gradients, run the one full-N pass, seed best.

    Under ``axis_name`` (SPMD, see :func:`_round_fused`): rows are this
    rank's shard, quantization scales are pmaxed so every rank encodes
    int8 gradients on the same grid, and the root histogram is merged
    with the same collective the rounds use.  With ``dcn_axis_name`` the
    histogram merge stays per-slice (axis_name only) and the root split
    election goes through the same two-level top-k exchange the rounds
    use; scalar totals and quant scales merge across BOTH axes.  With
    ``feature_axis_name`` (2-D mesh) the root histogram over the local
    (F/d_f, N/d_r) tile is already complete for the owned feature block
    after the row-axis merge — ZERO feature-axis collectives — and the
    root election runs the owned-feature search; row-domain totals merge
    over the row axes only (rows are replicated across feature blocks)
    while the quant-scale pmax spans every axis (idempotent: pins
    cross-block grid consistency)."""
    f, n = bins_t.shape
    L = num_leaves
    grad = grad.astype(jnp.float32) * sample_weight
    hess = hess.astype(jnp.float32) * sample_weight
    grad_true, hess_true = grad, hess
    sum_axes = tuple(a for a in (axis_name, dcn_axis_name) if a is not None)
    all_axes = sum_axes + (
        (feature_axis_name,) if feature_axis_name is not None else ())

    def pmaxg(x):
        return jax.lax.pmax(x, all_axes) if all_axes else x

    gq = hq = quant_scale = None
    if quantize_bins:
        half = max(quantize_bins // 2, 1)
        inbag = row_mask.astype(jnp.float32)
        g_scale = jnp.maximum(
            pmaxg(jnp.max(jnp.abs(grad) * inbag)) / half, 1e-30)
        h_scale = jnp.maximum(
            pmaxg(jnp.max(hess * inbag)) / quantize_bins, 1e-30)
        gs, hs = grad / g_scale, hess / h_scale
        if stochastic_rounding:
            kg, kh = jax.random.split(
                quant_key if quant_key is not None else jax.random.PRNGKey(0))
            gqf = jnp.floor(gs + jax.random.uniform(kg, gs.shape))
            hqf = jnp.floor(hs + jax.random.uniform(kh, hs.shape))
        else:
            gqf, hqf = jnp.round(gs), jnp.round(hs)
        gq = jnp.clip(gqf, -127, 127).astype(jnp.int8)
        hq = jnp.clip(hqf, 0, 127).astype(jnp.int8)
        grad = gq.astype(jnp.float32) * g_scale
        hess = hq.astype(jnp.float32) * h_scale
        quant_scale = jnp.stack([g_scale, h_scale, jnp.float32(1.0)])

    hist_src = (bins_t if efb_bins_t is None else efb_bins_t).T

    def unbundle1(h):
        if efb_gather is None:
            return h[0]
        return unbundle_hists(h, efb_gather, efb_default, f, num_bins)[0]

    if quantize_bins and use_pallas:
        hist0 = unbundle1(histogram_multi_quantized(
            hist_src, gq, hq, row_mask, jnp.zeros((n,), jnp.int32), 0, 1,
            num_bins)).astype(jnp.float32) * quant_scale[:, None, None]
    elif use_pallas:
        hist0 = unbundle1(histogram_multi(
            hist_src, grad, hess, row_mask, jnp.zeros((n,), jnp.int32), 0, 1,
            num_bins, precision=hist_precision))
    else:
        hist0 = unbundle1(histogram(
            hist_src, grad, hess, row_mask.astype(jnp.float32), num_bins,
            strategy="scatter")[None])
    # totals from feature 0 of the LOCAL hist, summed across ranks (a
    # 3-scalar psum); the histogram itself merges with the round's
    # collective — psum (replicated) or psum_scatter (owned F/R slice)
    sum0 = jnp.sum(hist0[:, 0, :], axis=1)  # totals from feature 0: (3,)
    if sum_axes:  # row-domain: every feature block's local feature 0
        # already holds ALL local rows (each row lands in one bin per
        # feature, padded dead features in bin 0) — summing the feature
        # axis too would over-count by d_f
        sum0 = jax.lax.psum(sum0, sum_axes)
    if axis_name is not None:
        if merge == "scatter":
            hist0 = jax.lax.psum_scatter(
                hist0, axis_name, scatter_dimension=1, tiled=True)
        else:
            hist0 = jax.lax.psum(hist0, axis_name)
    g0, h0, c0 = sum0[0], sum0[1], sum0[2]
    leaf_out0 = leaf_output(g0, h0, params)

    tree0 = TreeArrays(
        num_leaves=jnp.asarray(1, jnp.int32),
        split_feature=jnp.zeros((L - 1,), jnp.int32),
        threshold_bin=jnp.zeros((L - 1,), jnp.int32),
        default_left=jnp.zeros((L - 1,), bool),
        split_gain=jnp.zeros((L - 1,), jnp.float32),
        left_child=jnp.zeros((L - 1,), jnp.int32),
        right_child=jnp.zeros((L - 1,), jnp.int32),
        internal_value=jnp.zeros((L - 1,), jnp.float32),
        internal_weight=jnp.zeros((L - 1,), jnp.float32),
        internal_count=jnp.zeros((L - 1,), jnp.float32),
        leaf_value=jnp.zeros((L,), jnp.float32),
        leaf_weight=jnp.zeros((L,), jnp.float32),
        leaf_count=jnp.zeros((L,), jnp.float32),
        leaf_sum_g=jnp.zeros((L,), jnp.float32),
        leaf_depth=jnp.zeros((L,), jnp.int32),
        is_cat=jnp.zeros((L - 1,), bool),
        cat_mask=jnp.zeros((L - 1, num_bins), bool),
    )
    nb_l, mb_l, fm_l, cm_l, fc_l, f0_off = _split_tables(
        axis_name, merge, hist0.shape[1], num_bins_pf, missing_bin_pf,
        feature_mask, categorical_mask, feature_contri,
        feature_axis_name=feature_axis_name)
    if dcn_axis_name is not None:
        from ..parallel.hierarchy import dcn_topk_best

        bb0 = dcn_topk_best(
            hist0[None], jnp.asarray([g0]), jnp.asarray([h0]),
            jnp.asarray([c0]), nb_l, mb_l, fm_l, cm_l, fc_l,
            params=params, top_k=dcn_top_k, dcn_axis=dcn_axis_name,
            depth=jnp.asarray([0.0], jnp.float32),
            parent_out=jnp.asarray([leaf_out0]))
    else:
        bb0 = _batched_best(
            hist0[None], jnp.asarray([g0]), jnp.asarray([h0]),
            jnp.asarray([c0]), nb_l, mb_l, params,
            fm_l, cm_l, None, None,
            jnp.asarray([-jnp.inf], jnp.float32),
            jnp.asarray([jnp.inf], jnp.float32),
            None, jnp.asarray([0], jnp.int32), rng_key,
            depth=jnp.asarray([0.0], jnp.float32),
            parent_out=jnp.asarray([leaf_out0]),
            feature_contri=fc_l,
        )
    best0 = _set_best(
        _empty_best(L, num_bins), jnp.asarray(0),
        jax.tree.map(lambda a: a[0], _merge_best(
            bb0, feature_axis_name if feature_axis_name is not None
            else axis_name, f0_off)),
    )
    state = WState(
        order=jnp.arange(n, dtype=jnp.int32),
        leaf_start=jnp.zeros((L,), jnp.int32),
        leaf_cnt=jnp.zeros((L,), jnp.int32).at[0].set(n),
        leaf_id=jnp.zeros((n,), jnp.int32),
        hist=jnp.zeros((L, 3, hist0.shape[1], num_bins),
                       jnp.float32).at[0].set(hist0),
        best=best0,
        leaf_sum_g=jnp.zeros((L,), jnp.float32).at[0].set(g0),
        leaf_sum_h=jnp.zeros((L,), jnp.float32).at[0].set(h0),
        leaf_count=jnp.zeros((L,), jnp.float32).at[0].set(c0),
        leaf_depth=jnp.zeros((L,), jnp.int32),
        leaf_parent=jnp.full((L,), -1, jnp.int32),
        leaf_side=jnp.zeros((L,), jnp.int32),
        num_leaves_cur=jnp.asarray(1, jnp.int32),
        leaf_out=jnp.zeros((L,), jnp.float32).at[0].set(leaf_out0),
        tree=tree0,
    )
    return state, grad, hess, gq, hq, quant_scale, grad_true, hess_true


@functools.partial(jax.jit, static_argnames=("params", "quant_renew",
                                             "axis_name", "dcn_axis_name",
                                             "feature_axis_name"))
def _w_finalize(state: WState, grad_true, hess_true, row_mask,
                *, params: SplitParams, quant_renew: bool,
                axis_name: Optional[str] = None,
                dcn_axis_name: Optional[str] = None,
                feature_axis_name: Optional[str] = None):
    # `feature_axis_name` is accepted for uniform static threading on the
    # 2-D mesh but contributes NO collective: every sum here is
    # row-domain (rows are replicated across feature blocks — summing
    # the feature axis would over-count by d_f) and the inputs are
    # already feature-replicated.
    L = state.leaf_out.shape[0]
    sum_axes = tuple(a for a in (axis_name, dcn_axis_name) if a is not None)
    if quant_renew:
        mrow = row_mask.astype(jnp.float32)
        Gt = jnp.zeros((L,), jnp.float32).at[state.leaf_id].add(
            grad_true * mrow)
        Ht = jnp.zeros((L,), jnp.float32).at[state.leaf_id].add(
            hess_true * mrow)
        if sum_axes:  # true-gradient renewal sums the ROW axes
            Gt = jax.lax.psum(Gt, sum_axes)
            Ht = jax.lax.psum(Ht, sum_axes)
        leaf_value = leaf_output(Gt, Ht, params)
    else:
        leaf_value = leaf_output(state.leaf_sum_g, state.leaf_sum_h, params)
    active = jnp.arange(L, dtype=jnp.int32) < state.num_leaves_cur
    tree = state.tree._replace(
        num_leaves=state.num_leaves_cur,
        leaf_value=jnp.where(active, leaf_value, 0.0),
        leaf_weight=jnp.where(active, state.leaf_sum_h, 0.0),
        leaf_count=jnp.where(active, state.leaf_count, 0.0),
        leaf_sum_g=jnp.where(active, state.leaf_sum_g, 0.0),
        leaf_depth=state.leaf_depth,
    )
    return tree, state.leaf_id


def _grow_windowed_impl(
    bins_t: jnp.ndarray,  # (F, N) int16 feature-major
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    row_mask: jnp.ndarray,
    sample_weight: jnp.ndarray,
    feature_mask: jnp.ndarray,
    num_bins_pf: jnp.ndarray,
    missing_bin_pf: jnp.ndarray,
    rng_key: Optional[jnp.ndarray] = None,
    quant_key: Optional[jnp.ndarray] = None,
    feature_contri: Optional[jnp.ndarray] = None,
    categorical_mask: Optional[jnp.ndarray] = None,
    efb_bins_t: Optional[jnp.ndarray] = None,  # (F_b, N) bundled matrix
    efb_gather: Optional[jnp.ndarray] = None,
    efb_default: Optional[jnp.ndarray] = None,
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    leaf_tile: int = 16,
    hist_precision: str = "f32",
    use_pallas: bool = True,
    quantize_bins: int = 0,
    stochastic_rounding: bool = True,
    quant_renew: bool = False,
    stats: Optional[dict] = None,
    guard_label: str = "",
    megakernel: bool = False,
    mk_interpret: bool = False,
) -> tuple[TreeArrays, jnp.ndarray]:
    """Host-driven windowed growth; returns (tree, leaf_id per row).

    One donated dispatch per round, zero blocking host syncs in steady
    state (module docstring).  ``stats``, when given, receives the
    driver's dispatch/sync ledger: {rounds, dispatches, host_syncs,
    async_resolves, retries, windows} — what tests/test_retrace.py pins.
    """
    common = dict(num_leaves=num_leaves, num_bins=num_bins, params=params,
                  leaf_tile=leaf_tile)
    state, g_d, h_d, gq, hq, qs, g_true, h_true = _w_init(
        bins_t, grad, hess, row_mask, sample_weight, num_bins_pf,
        missing_bin_pf, feature_mask, rng_key, quant_key, feature_contri,
        categorical_mask, efb_bins_t, efb_gather, efb_default,
        use_pallas=use_pallas, quantize_bins=quantize_bins,
        hist_precision=hist_precision,
        stochastic_rounding=stochastic_rounding, **common)

    n = bins_t.shape[1]
    # the Pallas segment partition is the TPU default; LGBMTPU_PARTITION
    # _PALLAS=0 drops to the O(N) XLA permutation (same results), as does
    # a prior kernel failure recorded in the degradation registry (folded
    # into the jit static here so post-failure traces skip the kernel)
    pallas_partition = use_pallas and (
        os.environ.get("LGBMTPU_PARTITION_PALLAS", "1") != "0") and (
        _degrade.available(_degrade.PARTITION))
    if megakernel and _obs.enabled():
        # host-side static — zero extra dispatches/syncs (the budget pin
        # in tests/test_retrace.py runs with the megakernel ON)
        _obs.counter("train_megakernel_trees_total").inc()

    def round_fn(st, W):
        st, info = _round_fused(
            st, bins_t, g_d, h_d, gq, hq, qs, row_mask,
            num_bins_pf, missing_bin_pf, feature_mask, rng_key,
            feature_contri, categorical_mask,
            efb_bins_t, efb_gather, efb_default,
            max_depth=max_depth, W=W, use_pallas=use_pallas,
            quantize_bins=quantize_bins, hist_precision=hist_precision,
            has_cat=categorical_mask is not None,
            pallas_partition=pallas_partition, megakernel=megakernel,
            mk_interpret=mk_interpret, **common)
        return st, info

    # round 1 needs no feedback: a round's window (the small children)
    # can never exceed floor(N/2) rows, whatever it admits
    state = _run_fused_rounds(
        round_fn, state, n_ladder=n,
        w_first=_window_size(max(n // 2, 1), n),
        num_leaves=num_leaves, stats=stats, guard_label=guard_label)

    return _w_finalize(state, g_true, h_true, row_mask, params=params,
                       quant_renew=bool(quant_renew and quantize_bins))


def _run_fused_rounds(round_fn, state, *, n_ladder: int, w_first: int,
                      num_leaves: int, stats: Optional[dict],
                      guard_label: str, floor: int = 8192):
    """The one-dispatch/zero-sync round protocol (module docstring),
    factored out of :func:`_grow_windowed_impl` so the SPMD driver
    (parallel/data_parallel.py::grow_tree_windowed_data_parallel) runs
    the IDENTICAL host loop — same W ladder, same one-round-behind async
    info reads, same drain, same dispatch/sync accounting and telemetry —
    over a shard_mapped round.  ``round_fn(state, W) -> (state', info)``
    must be a single donated dispatch; ``n_ladder`` is the row count the
    W ladder quantizes against (the LOCAL shard size under SPMD: W bounds
    each rank's own window).  ``floor`` is the ladder's minimum rung:
    8192 per ROUND for the solo/SPMD growers (compile-cost bound — each W
    is its own Mosaic compile), but a BATCHED round (treegrow_fleet.py)
    quantizes the floor on the total live window across the batch, so
    its per-lane floor shrinks as 8192/B; W padding is row masking only,
    so the grown trees are bitwise invariant to the floor."""
    prof = os.environ.get("LGBMTPU_WPROF") == "1"
    enforce = os.environ.get("LGBMTPU_DISPATCH_BUDGET") == "1"
    n = n_ladder
    W = w_first
    pending: list = []  # dispatched rounds whose info is still in flight
    n_leaves = 1
    rounds = 0
    retries = 0
    windows: list = []
    import time as _time
    t_open = _time.perf_counter()
    # span anchor: per-round spans close ONLY at the accounted async-info
    # resolves below (the round-7 protocol's existing sync points), so the
    # intervals are device-inclusive without adding a single pull — the
    # pattern jaxlint R10 pins for span closes
    t_resolve_prev: Optional[float] = None
    rung_prev: Optional[int] = None  # last resolved round's ladder rung
    t_last = _time.perf_counter() if prof else 0.0
    # every productive round admits >= 1 split, reads lag 1 round, plus
    # defensive headroom for retried (skipped) rounds
    max_rounds = 2 * num_leaves + 4
    converged = False
    resolved = 0  # rounds whose info the host has read (lags `rounds` by 1)
    counter = _san.DispatchCounter()
    counter.__enter__()
    try:
        while rounds < max_rounds:
            _san.record_dispatch()
            state, info_d = round_fn(state, W)
            _san.async_pull_start(info_d)
            pending.append(info_d)
            rounds += 1
            windows.append(W)
            if len(pending) < 2:
                continue  # pipeline fill: resolve reads one dispatch behind
            info = _san.async_pull_result(pending.pop(0))
            k_acc, total, ok, whint, finite = (int(info[0]), int(info[1]),
                                               int(info[2]), int(info[3]),
                                               int(info[4]))
            w_ran = windows[resolved]  # the W THIS round ran with (the loop
            # variable has moved on to later dispatches)
            resolved += 1
            # telemetry rides the values the async protocol ALREADY pulled —
            # host dict updates only, zero extra dispatches/syncs (the
            # DispatchCounter budget pin runs with this enabled)
            if _obs.enabled():
                _obs.histogram("train_window_rows").observe(total)
                _obs.histogram("train_window_fill").observe(
                    total / max(w_ran, 1))
                # the resolve we just did IS an accounted sync: the
                # resolve-to-resolve interval is the honest wall clock of
                # the round that retired between them (the first one also
                # carries init + pipeline fill, flagged in the attrs)
                t_now = _time.perf_counter()
                # W-ladder context (round 12): the rung this round ran
                # on, the transition that brought it there, and the
                # whint that will ladder W two dispatches later — one
                # trace now answers whether whint overshoots the
                # realized windows (rows vs W per rung)
                rung = _window_rung(w_ran, n, floor)
                _trace.record_span(
                    "windowed_round",
                    t_now - (t_resolve_prev if t_resolve_prev is not None
                             else t_open),
                    round=resolved, k_acc=k_acc, rows=total, W=w_ran,
                    rung=rung,
                    rung_delta=(0 if rung_prev is None
                                else rung - rung_prev),
                    whint=whint,
                    first=t_resolve_prev is None)
                t_resolve_prev = t_now
                rung_prev = rung
            if not finite:
                _obs.counter("train_nonfinite_errors_total").inc()
                _obs.event("nonfinite", phase="windowed", round=resolved)
                raise NonFiniteError(
                    f"non-finite gradients/hessians/split stats on device "
                    f"at windowed round {resolved}{guard_label}: refusing "
                    "to keep boosting on NaNs. The guard rode the round's "
                    "async info vector (read one round behind, zero extra "
                    "dispatches/syncs) — check labels/weights/custom "
                    "objective outputs; see docs/ROBUSTNESS.md")
            if prof:
                t_now = _time.perf_counter()
                print(f"[WPROF] k={k_acc:2d} total={total:7d} W={w_ran:7d} "
                      f"round={t_now - t_last:6.3f}s", flush=True)
                t_last = t_now
            if not ok:
                # prediction breached (whint bound violated — a bug, not a
                # workload property): the device skipped the round; fold the
                # corrected W into the next dispatch instead of syncing
                retries += 1
                W = _window_size(max(total, 1), n, floor)
                continue
            n_leaves += k_acc
            if k_acc == 0 or n_leaves >= num_leaves:
                converged = True
                break
            W = _window_size(max(whint, 1), n, floor)
        # drain the in-flight round's info so its finite flag is checked
        # too (the pipeline runs one dispatch ahead of the resolve point;
        # without the drain, corruption in the final rounds would slip
        # past the in-loop guard and only be caught by the deferred
        # booster-level check)
        while pending:
            info = _san.async_pull_result(pending.pop(0))
            resolved += 1
            if _obs.enabled():
                # drained rounds get their span too — the trace must hold
                # exactly `rounds` windowed_round spans per tree (the last
                # round of a tree resolves HERE, one dispatch behind), and
                # this resolve is just as accounted as the in-loop one
                t_now = _time.perf_counter()
                rung = _window_rung(windows[resolved - 1], n, floor)
                _trace.record_span(
                    "windowed_round",
                    t_now - (t_resolve_prev if t_resolve_prev is not None
                             else t_open),
                    round=resolved, k_acc=int(info[0]), rows=int(info[1]),
                    W=windows[resolved - 1],
                    rung=rung,
                    rung_delta=(0 if rung_prev is None
                                else rung - rung_prev),
                    whint=int(info[3]),
                    first=t_resolve_prev is None, drained=True)
                t_resolve_prev = t_now
                rung_prev = rung
            if not int(info[4]):
                _obs.counter("train_nonfinite_errors_total").inc()
                _obs.event("nonfinite", phase="windowed_drain",
                           round=resolved)
                raise NonFiniteError(
                    f"non-finite gradients/hessians/split stats on device "
                    f"at windowed round {resolved}{guard_label} (drained "
                    "in-flight round): refusing to finalize a tree grown "
                    "on NaNs; see docs/ROBUSTNESS.md")
    finally:
        pending.clear()
        counter.__exit__(None, None, None)
        if stats is not None:
            stats.update(rounds=rounds, dispatches=counter.dispatches,
                         host_syncs=counter.host_syncs,
                         async_resolves=counter.async_resolves,
                         retries=retries, windows=windows)
        if _obs.enabled():
            # per-tree summary from the driver's own host-side ledger
            _obs.counter("train_windowed_rounds_total").inc(rounds)
            _obs.counter("train_windowed_retries_total").inc(retries)
            _obs.event("windowed_tree", rounds=rounds, retries=retries,
                       dispatches=counter.dispatches,
                       host_syncs=counter.host_syncs,
                       async_resolves=counter.async_resolves)
            # tree-level span closing here, right after the drain loop's
            # final accounted resolve emptied `pending` — every dispatched
            # round's info has been read, so the interval covers the whole
            # tree's device work without adding a sync
            _trace.record_span("windowed_tree",
                               _time.perf_counter() - t_open,
                               rounds=rounds, retries=retries,
                               dispatches=counter.dispatches)
    if not converged:
        # the safety headroom ran out (repeated window-bound breaches):
        # growth stopped early with a valid but under-grown tree — make
        # that LOUD even without the enforce gate armed
        from ..utils.log import log_warning
        log_warning(
            f"windowed growth exhausted its round budget ({max_rounds} "
            f"dispatches, {retries} window retries) before reaching "
            f"num_leaves={num_leaves}; the tree is valid but under-grown "
            "— this indicates a whint bound violation, please report")

    if enforce:
        counter.assert_round_budget(rounds, what="windowed round loop")
        if retries:
            raise _san.BudgetError(
                f"windowed round loop: {retries} window-prediction "
                "retries — the whint bound under-predicted (see "
                "ops/treegrow_windowed.py round-7 notes)")

    return state


def megakernel_mode(use_pallas_eff: bool, *, rng_key=None, efb_bins_t=None,
                    quantize_bins: int = 0, mode: Optional[str] = None,
                    loud: bool = True) -> tuple[bool, bool]:
    """The round-megakernel gate, shared by the single-device entry below
    and the SPMD entry (parallel/data_parallel.py): returns
    ``(megakernel, mk_interpret)`` statics for :func:`_round_fused`.

    ``mode`` (the Booster's ``megakernel`` extra param, models/gbdt.py)
    overrides ``LGBMTPU_MEGAKERNEL``; both select: ``auto`` (default —
    ON wherever the Pallas hot path runs), ``1`` (forced ON),
    ``interpret`` (ON through the Mosaic interpreter — the off-chip
    correctness harness, which IGNORES the degradation registry exactly
    like the partition kernel's interpret path: a degraded process must
    re-run the kernel and surface, never silently grow three-pass
    trees), ``0`` (OFF).

    The megakernel envelope excludes EFB bundles, per-node feature
    sampling (the rng-keyed scan cannot run on-core), and — on the
    Pallas hot path — int8-quantized training: the three-pass round
    accumulates quantized histograms exactly on the int8 MXU while the
    committed megakernel folds the DEQUANTIZED f32 values (bitwise with
    the XLA round, NOT with the int8 kernel), so until the int8 MXU
    accumulate variant lands (docs/NEXT.md) a quantized+Pallas config
    must not silently change numerics.  Every excluded-but-requested
    configuration falls back to the three-pass round LOUDLY — counter +
    event, never a silent divergence — exactly like the degradation
    registry's kernel-failure fallback."""
    if mode is None:
        mode = os.environ.get("LGBMTPU_MEGAKERNEL", "auto")
    mode = str(mode).lower()
    if mode in ("0", "off"):
        return False, False
    if mode != "interpret" and not _degrade.available(_degrade.ROUND):
        return False, False
    requested = mode in ("1", "interpret") or (mode == "auto"
                                               and use_pallas_eff)
    if not requested:
        return False, False
    reason = None
    if efb_bins_t is not None:
        reason = "efb"
    elif rng_key is not None:
        reason = "node_rng"
    elif quantize_bins and use_pallas_eff:
        reason = "quantized_mxu"
    if reason is not None:
        if loud:
            _obs.counter("megakernel_envelope_fallbacks_total").inc()
            _obs.event("megakernel_fallback", reason=reason)
        return False, False
    return True, mode == "interpret"


def grow_tree_windowed(*args, use_pallas: bool = True,
                       megakernel_opt: Optional[str] = None, **kwargs):
    """Public entry: :func:`_grow_windowed_impl` behind the graceful
    kernel-degradation net (utils/degrade.py).

    ``use_pallas`` is folded with the degradation registry BEFORE it
    becomes a jit static, so a process that already lost its Pallas
    kernels traces straight to the XLA paths.  A Pallas/Mosaic failure
    that only surfaces at backend-compile or execute time escapes the
    trace-time dispatchers — it is caught here once, logged, recorded,
    and the whole tree is regrown from the ORIGINAL inputs on the XLA
    path (only internal WState buffers were donated to the failed
    dispatch; the grower inputs are intact).

    The net is LAYERED for the round megakernel: a megakernel failure
    disables only :data:`~..utils.degrade.ROUND` and regrows on the
    three-pass round (which may still use the Pallas hist + partition
    kernels); a histogram-kernel failure there degrades HIST as before.
    In ``LGBMTPU_MEGAKERNEL=interpret`` mode failures SURFACE (the
    correctness harness must never silently fall back, mirroring the
    partition kernel's interpret contract)."""
    use_p = use_pallas and _degrade.available(_degrade.HIST)
    rng_key = args[8] if len(args) > 8 else kwargs.get("rng_key")
    efb_bins_t = args[12] if len(args) > 12 else kwargs.get("efb_bins_t")
    mk, mk_interp = megakernel_mode(
        use_p, rng_key=rng_key, efb_bins_t=efb_bins_t,
        quantize_bins=kwargs.get("quantize_bins", 0), mode=megakernel_opt)

    def three_pass():
        if not use_p:
            return _grow_windowed_impl(*args, use_pallas=False, **kwargs)
        return _degrade.run_with_fallback(
            _degrade.HIST,
            lambda: _grow_windowed_impl(*args, use_pallas=True, **kwargs),
            lambda: _grow_windowed_impl(*args, use_pallas=False, **kwargs))

    if not mk:
        return three_pass()
    if mk_interp:
        # correctness harness: always run the kernel (the degradation
        # registry is ignored by megakernel_mode) and surface every
        # failure — the partition kernel's interpret contract
        from ..utils import faults as _faults

        _faults.maybe_fail("pallas_round")
        return _grow_windowed_impl(*args, use_pallas=use_p, megakernel=True,
                                   mk_interpret=True, **kwargs)
    return _degrade.run_with_fallback(
        _degrade.ROUND,
        lambda: _grow_windowed_impl(*args, use_pallas=use_p, megakernel=True,
                                    mk_interpret=False, **kwargs),
        three_pass, fault_site="pallas_round")
