"""Histogram construction on device.

TPU-native replacement for the reference's histogram inner loops
(reference: src/io/dense_bin.hpp -> DenseBin::ConstructHistogram,
src/io/multi_val_dense_bin.hpp, src/treelearner/cuda/cuda_histogram_constructor.cu).

The reference accumulates (sum_grad, sum_hess) per bin with 4-way unrolled
scalar loops (CPU) or shared-memory atomics (CUDA).  TPUs have neither scalar
loops nor atomics; instead we express the histogram as an XLA scatter-add over
a flat (F*B) index space, which XLA lowers to a deterministic on-device
combiner.  A one-hot-matmul (MXU) variant is provided for wide-row tiles and
picked by a cost model, mirroring TrainingShareStates' col-wise/row-wise
choice (reference: src/io/train_share_states.cpp).

Channels: 0 = sum_grad, 1 = sum_hess, 2 = count (reference keeps 2 doubles and
recovers count; we keep an explicit count channel since f32 hessians do not
always encode counts).  Layout is CHANNEL-FIRST (3, F, B) / (L, 3, F, B)
everywhere — a trailing channel dim of 3 forces TPU tiled layouts to pad
the minor pair (B, 3) -> (B, 128) = 42.7x in every hist copy (measured,
docs/PERF_NOTES.md), while (F, B) minor tiles pad ~nothing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NUM_CHANNELS = 3


def histogram_scatter(
    bins: jnp.ndarray,  # (N, F) int
    grad: jnp.ndarray,  # (N,) f32
    hess: jnp.ndarray,  # (N,) f32
    mask: jnp.ndarray,  # (N,) bool or f32 — rows contributing to this hist
    num_bins: int,
) -> jnp.ndarray:
    """Masked histogram over all features: returns (3, F, B) f32.

    Rows with mask=0 contribute zeros (they still scatter, but with zero
    payload) — this is the TPU analogue of histogramming only the rows of one
    leaf (reference: Dataset::ConstructHistograms with use_indices=true).
    """
    n, f = bins.shape
    m = mask.astype(grad.dtype)
    flat_idx = bins.astype(jnp.int32) + (jnp.arange(f, dtype=jnp.int32) * num_bins)[None, :]
    payload = jnp.stack([grad * m, hess * m, m], axis=0)  # (3, N)
    payload = jnp.broadcast_to(payload[:, :, None], (NUM_CHANNELS, n, f))
    hist = jnp.zeros((NUM_CHANNELS, f * num_bins), dtype=grad.dtype)
    hist = hist.at[:, flat_idx].add(payload, mode="drop")
    return hist.reshape(NUM_CHANNELS, f, num_bins)


def histogram_onehot_matmul(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    mask: jnp.ndarray,
    num_bins: int,
    row_tile: int = 8192,
) -> jnp.ndarray:
    """MXU variant: one-hot(bin) contracted against (grad, hess, 1) payloads.

    For a row tile of size T this is F batched (B x T)@(T x 3) matmuls — the
    systolic-array-friendly formulation of histogramming (SURVEY.md §10.1
    strategy 1).  Processes rows in tiles via lax.scan to bound memory.
    """
    n, f = bins.shape
    m = mask.astype(grad.dtype)
    payload = jnp.stack([grad * m, hess * m, m], axis=-1)  # (N, 3)

    pad = (-n) % row_tile
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        payload = jnp.pad(payload, ((0, pad), (0, 0)))
    nt = (n + pad) // row_tile
    bins_t = bins.reshape(nt, row_tile, f)
    pay_t = payload.reshape(nt, row_tile, NUM_CHANNELS)

    def body(acc, inp):
        b_tile, p_tile = inp  # (T, F), (T, 3)
        onehot = jax.nn.one_hot(b_tile.T, num_bins, dtype=grad.dtype)  # (F, T, B)
        # (3, T) @ (F, T, B) -> (3, F, B)
        h = jnp.einsum("ftb,tc->cfb", onehot, p_tile, precision=jax.lax.Precision.HIGHEST)
        return acc + h, None

    init = jnp.zeros((NUM_CHANNELS, f, num_bins), dtype=grad.dtype)
    hist, _ = jax.lax.scan(body, init, (bins_t, pay_t))
    return hist


def histogram_onehot_multi(
    bins: jnp.ndarray,  # (N, F) int
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    mask: jnp.ndarray,  # (N,) in-bag mask
    leaf_id: jnp.ndarray,  # (N,) i32 current leaf per row
    leaf_base: int,
    num_leaves_tile: int,
    num_bins: int,
    *,
    precision: str = "f32",
    row_tile: int = 8192,
) -> jnp.ndarray:
    """Per-leaf histograms for a tile of leaves in ONE data pass, pure-XLA
    einsum formulation -> (L_tile, 3, F, B) f32.

    Same contract as hist_pallas.histogram_pallas_multi; payload lanes are
    leaf-onehot x bf16x2-split (grad, hess, count) so products carry ~17
    mantissa bits with f32 accumulation.  Measured (v5e, in-jit): at
    num_bins <= 64 XLA's fused one-hot einsum beats the Pallas kernel
    (~4 ms vs ~8-10 ms per 1M x 28 pass); at 256 bins the Pallas kernel
    wins (~10 ms vs ~25 ms) — histogram strategy is selected per max_bin
    by the grower (the TrainingShareStates cost-model analogue)."""
    from .hist_pallas import _split_bf16x2

    n, f = bins.shape
    m = mask.astype(jnp.float32)
    g = grad.astype(jnp.float32) * m
    h = hess.astype(jnp.float32) * m
    if precision == "f32":
        g_hi, g_lo = _split_bf16x2(g)
        h_hi, h_lo = _split_bf16x2(h)
        base = jnp.stack([g_hi, h_hi, m, g_lo, h_lo, jnp.zeros_like(m)], axis=-1)
    elif precision == "bf16":
        base = jnp.stack([g, h, m], axis=-1)
    else:
        raise ValueError(precision)
    ncl = base.shape[-1]
    lid = leaf_id.astype(jnp.int32) - leaf_base
    onehot_l = (
        lid[:, None] == jnp.arange(num_leaves_tile, dtype=jnp.int32)[None, :]
    ).astype(jnp.float32)  # (N, L_tile)
    payload = (onehot_l[:, :, None] * base[:, None, :]).reshape(
        n, num_leaves_tile * ncl
    )
    c = payload.shape[1]

    pad = (-n) % row_tile
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        payload = jnp.pad(payload, ((0, pad), (0, 0)))
    nt = (n + pad) // row_tile
    bins_t = bins.reshape(nt, row_tile, f)
    pay_t = payload.astype(jnp.bfloat16).reshape(nt, row_tile, c)

    def body(acc, inp):
        b_tile, p_tile = inp
        onehot = jax.nn.one_hot(b_tile.T, num_bins, dtype=jnp.bfloat16)  # (F, T, B)
        # natural dot output (f, b, c) — the CPU backend's dot thunk
        # rejects the lhs/rhs swap a "->cfb" spec induces for bf16 inputs
        hh = jnp.einsum("ftb,tc->fbc", onehot, p_tile,
                        preferred_element_type=jnp.float32)
        return acc + hh, None

    init = jnp.zeros((f, num_bins, c), jnp.float32)
    hist, _ = jax.lax.scan(body, init, (bins_t, pay_t))
    # one transpose per pass to the package's channel-first layout
    hist = jnp.transpose(hist, (2, 0, 1)).reshape(
        num_leaves_tile, ncl, f, num_bins)
    if precision == "f32":
        out3 = jnp.stack(
            [hist[:, 0] + hist[:, 3], hist[:, 1] + hist[:, 4], hist[:, 2]],
            axis=1,
        )  # (L_tile, 3, F, B)
    else:
        out3 = hist
    return out3


def histogram_onehot_multi_quantized(
    bins: jnp.ndarray,  # (N, F) int
    grad_q: jnp.ndarray,  # (N,) int8 — discretized gradients
    hess_q: jnp.ndarray,  # (N,) int8 — discretized hessians (non-negative)
    mask: jnp.ndarray,  # (N,) in-bag mask
    leaf_id: jnp.ndarray,  # (N,) i32 current leaf per row
    leaf_base: int,
    num_leaves_tile: int,
    num_bins: int,
    *,
    row_tile: int = 8192,
) -> jnp.ndarray:
    """Quantized per-leaf histograms, pure-XLA int8 one-hot dot ->
    (L_tile, 3, F, B) int32 with EXACT integer accumulation (reference:
    gradient_discretizer.cpp int16/int32 histogram buffers).

    The narrow-bin sibling of hist_pallas.histogram_pallas_multi_quantized:
    at num_bins <= 64 the XLA fused one-hot einsum beats the Pallas kernel
    for the float path (measured, see histogram_onehot_multi) and the same
    selection applies to the int path — int8 x int8 dots accumulate in
    int32 on the MXU, so exactness is preserved."""
    from .hist_pallas import quantized_leaf_payload

    n, f = bins.shape
    ncl = 3
    payload = quantized_leaf_payload(grad_q, hess_q, mask, leaf_id,
                                     leaf_base, num_leaves_tile)
    c = payload.shape[1]

    pad = (-n) % row_tile
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        payload = jnp.pad(payload, ((0, pad), (0, 0)))
    nt = (n + pad) // row_tile
    bins_t = bins.reshape(nt, row_tile, f)
    pay_t = payload.reshape(nt, row_tile, c)

    def body(acc, inp):
        b_tile, p_tile = inp
        onehot = jax.nn.one_hot(b_tile.T, num_bins, dtype=jnp.int8)  # (F,T,B)
        # natural dot output (f, b, c) — see histogram_onehot_multi
        hh = jnp.einsum("ftb,tc->fbc", onehot, p_tile,
                        preferred_element_type=jnp.int32)
        return acc + hh, None

    init = jnp.zeros((f, num_bins, c), jnp.int32)
    hist, _ = jax.lax.scan(body, init, (bins_t, pay_t))
    return jnp.transpose(hist, (2, 0, 1)).reshape(
        num_leaves_tile, ncl, f, num_bins)  # (L_tile, 3, F, B)


def histogram_multi(
    bins: jnp.ndarray,  # (N, F) int
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    mask: jnp.ndarray,
    leaf_id: jnp.ndarray,
    leaf_base: int,
    num_leaves_tile: int,
    num_bins: int,
    *,
    precision: str = "f32",
) -> jnp.ndarray:
    """Multi-leaf histogram DISPATCHER for the Pallas-eligible growers ->
    (L_tile, 3, F, B).

    Tries the Pallas kernel; a kernel failure (or an armed
    ``pallas_hist`` fault-injection site) is caught ONCE, logged, and
    permanently degrades this process to the XLA one-hot path — identical
    contract, no manual env var needed (utils/degrade.py).  The decision
    runs at trace time: callers fold ``utils.degrade.available`` into
    their ``use_pallas`` static so post-failure traces compile without
    the broken kernel."""
    from ..utils import degrade as _degrade

    def _pallas():
        from .hist_pallas import histogram_pallas_multi

        return histogram_pallas_multi(
            bins, grad, hess, mask, leaf_id, leaf_base, num_leaves_tile,
            num_bins, precision=precision)

    return _degrade.run_with_fallback(
        _degrade.HIST, _pallas,
        lambda: histogram_onehot_multi(
            bins, grad, hess, mask, leaf_id, leaf_base, num_leaves_tile,
            num_bins, precision=precision),
        fault_site="pallas_hist")


def histogram_multi_quantized(
    bins: jnp.ndarray,  # (N, F) int
    grad_q: jnp.ndarray,
    hess_q: jnp.ndarray,
    mask: jnp.ndarray,
    leaf_id: jnp.ndarray,
    leaf_base: int,
    num_leaves_tile: int,
    num_bins: int,
) -> jnp.ndarray:
    """Quantized sibling of :func:`histogram_multi` — same
    catch-once/degrade-forever dispatch over the int8 kernels."""
    from ..utils import degrade as _degrade

    def _pallas():
        from .hist_pallas import histogram_pallas_multi_quantized

        return histogram_pallas_multi_quantized(
            bins, grad_q, hess_q, mask, leaf_id, leaf_base,
            num_leaves_tile, num_bins)

    return _degrade.run_with_fallback(
        _degrade.HIST, _pallas,
        lambda: histogram_onehot_multi_quantized(
            bins, grad_q, hess_q, mask, leaf_id, leaf_base, num_leaves_tile,
            num_bins),
        fault_site="pallas_hist")


def histogram(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    mask: jnp.ndarray,
    num_bins: int,
    strategy: str = "auto",
) -> jnp.ndarray:
    """Dispatch between strategies (reference analogue: TrainingShareStates'
    col-wise vs row-wise cost model)."""
    if strategy == "auto":
        # scatter wins for many features / large bins; matmul for narrow bins.
        strategy = "onehot" if num_bins <= 64 and bins.shape[1] <= 512 else "scatter"
    if strategy == "onehot":
        return histogram_onehot_matmul(bins, grad, hess, mask, num_bins)
    return histogram_scatter(bins, grad, hess, mask, num_bins)


def unbundle_hists(h: jnp.ndarray, efb_gather: jnp.ndarray,
                   efb_default: jnp.ndarray, num_feature: int,
                   num_bins: int) -> jnp.ndarray:
    """(tile, 3, F_b, B) bundle hists -> (tile, 3, F, B) per-feature hists:
    gather each feature's non-default slots; its default-bin row is
    leaf_total - sum(non-default) (reference most-freq-bin subtraction; see
    io/efb.py).  Shared by the fast and windowed growers."""
    tile = h.shape[0]
    flat = h.reshape(tile, 3, -1)
    flat = jnp.concatenate([flat, jnp.zeros((tile, 3, 1), h.dtype)], axis=2)
    hf = flat[:, :, efb_gather.reshape(-1)].reshape(
        tile, 3, num_feature, num_bins)
    leaf_tot = jnp.sum(h[:, :, 0, :], axis=2)  # (tile, 3)
    nondef = jnp.sum(hf, axis=3)  # (tile, 3, F)
    fill = leaf_tot[:, :, None] - nondef
    return hf + jnp.where(
        efb_default[None, None], fill[..., None], jnp.zeros((), h.dtype))


def fix_histogram_subtract(parent: jnp.ndarray, child: jnp.ndarray) -> jnp.ndarray:
    """Sibling histogram by subtraction (reference: Dataset::FixHistogram /
    the histogram subtraction trick) — exact because bins are identical."""
    return parent - child
