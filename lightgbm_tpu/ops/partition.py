"""Leaf-ordered row partition maintenance.

The TPU analogue of the reference's DataPartition (src/treelearner/
data_partition.hpp): rows are kept PHYSICALLY grouped by leaf so histogram
passes can be windowed to [start, start+count) ranges whose cost is
proportional to live rows instead of N (docs/PERF_NOTES.md round-3 plan).

The reference partitions with per-thread index buffers; here a round's
splits are applied as ONE fixed-shape stable permutation over the full row
order.  Two interchangeable implementations sit behind
:func:`partition_rows`:

* :func:`stable_partition_ranges` (XLA, this module): segment-relative
  cumulative sums + one permutation scatter.  Exact, shape-stable, runs
  everywhere — but O(N) per round (measured ~41 ms at 1M rows on a v5e)
  even when the round only splits a few small segments.
* ``ops/partition_pallas.py``: a Pallas kernel that touches ONLY the
  split segments (the in-place ``DataPartition::Split`` analogue), used
  by the fused windowed round on TPU; its raw output is merged back over
  the untouched positions here with the ``seg_id`` mask the admit phase
  already computed.  v2 keeps its buffers HBM-resident and streams
  per-chunk DMA, so there is NO row cap — the kernel is taken at any N
  (the v1 650k-row VMEM-staging fallback is deleted).

Both return identical results; tests/test_partition.py pins the Pallas
kernel (interpret mode) against the XLA path on the same fixtures.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def partition_rows(
    order: jnp.ndarray,  # (N,) i32 — current row ids, grouped by leaf
    seg_id: jnp.ndarray,  # (N,) i32 — split-segment id per POSITION, -1 = not split
    seg_start: jnp.ndarray,  # (S,) i32
    seg_len: jnp.ndarray,  # (S,) i32
    go_left: jnp.ndarray,  # (N,) bool per POSITION
    *,
    use_pallas: bool = False,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Apply a round's stable segment partition; returns
    ``(new_order, left_counts)``.

    ``use_pallas`` selects the segment-proportional TPU kernel
    (``interpret=True`` runs the same kernel through the Pallas
    interpreter for off-chip tests); otherwise the O(N) XLA permutation.
    The choice is made at trace time — both paths are pure functions of
    the same inputs with identical outputs.  The v2 kernel is
    HBM-resident with per-chunk DMA staging, so it is taken at ANY row
    count (v1's >650k silent XLA fallback is gone; only
    ``LGBMTPU_PARTITION_PALLAS=0`` and the degradation registry opt out).
    """
    if use_pallas or interpret:
        from ..utils import degrade as _degrade
        from .partition_pallas import partition_pallas_segments

        def _pallas():
            raw, left_counts = partition_pallas_segments(
                order, seg_start, seg_len, go_left, interpret=interpret)
            return jnp.where(seg_id >= 0, raw, order), left_counts

        if interpret:
            # correctness harness: always run the kernel (ignore the
            # degradation registry) and surface every failure — a silent
            # fallback here would quietly test XLA against XLA
            from ..utils import faults as _faults

            _faults.maybe_fail("pallas_partition")
            return _pallas()

        # a kernel failure is caught ONCE, logged, and permanently degrades
        # this process to the XLA permutation — same results, O(N) instead
        # of segment-proportional (utils/degrade.py)
        return _degrade.run_with_fallback(
            _degrade.PARTITION, _pallas,
            lambda: stable_partition_ranges(
                order, seg_id, seg_start, seg_len, go_left),
            fault_site="pallas_partition")
    return stable_partition_ranges(order, seg_id, seg_start, seg_len, go_left)


@jax.jit
def stable_partition_ranges(
    order: jnp.ndarray,  # (N,) i32 — current row ids, grouped by leaf
    seg_id: jnp.ndarray,  # (N,) i32 — split-segment id per POSITION, -1 = not split
    seg_start: jnp.ndarray,  # (S,) i32 — start position of each segment
    seg_len: jnp.ndarray,  # (S,) i32 — length of each segment
    go_left: jnp.ndarray,  # (N,) bool per POSITION — split decision
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Stably partition every segment of `order` by `go_left` in one shot.

    Returns (new_order, left_counts (S,)).  Positions outside all segments
    are untouched.  reference: DataPartition::Split, vectorized over all of
    a round's split leaves at once.
    """
    n = order.shape[0]
    in_seg = seg_id >= 0
    sid = jnp.maximum(seg_id, 0)

    # segment-relative stable ranks via global cumsums restarted per segment:
    # rank_left(p) = (#left in segment up to p) - (#left in segment before start)
    left_f = (in_seg & go_left).astype(jnp.int32)
    right_f = (in_seg & ~go_left).astype(jnp.int32)
    cl = jnp.cumsum(left_f)
    cr = jnp.cumsum(right_f)
    start_pos = seg_start[sid]  # (N,) start position of my segment
    cl0 = jnp.where(start_pos > 0, cl[jnp.maximum(start_pos - 1, 0)], 0)
    cr0 = jnp.where(start_pos > 0, cr[jnp.maximum(start_pos - 1, 0)], 0)
    rank_l = cl - cl0  # 1-based among left rows of my segment
    rank_r = cr - cr0
    # per-segment left counts from the cumsum endpoints — O(S), and the
    # reason seg_len is a parameter
    seg_end = seg_start + jnp.maximum(seg_len - 1, 0)
    cl0_seg = jnp.where(seg_start > 0, cl[jnp.maximum(seg_start - 1, 0)], 0)
    n_left_seg = jnp.where(seg_len > 0, cl[seg_end] - cl0_seg, 0).astype(jnp.int32)

    dest = jnp.where(
        go_left,
        start_pos + rank_l - 1,
        start_pos + n_left_seg[sid] + rank_r - 1,
    )
    pos = jnp.arange(n, dtype=jnp.int32)
    dest = jnp.where(in_seg, dest, pos)
    new_order = jnp.zeros_like(order).at[dest].set(order)
    return new_order, n_left_seg
