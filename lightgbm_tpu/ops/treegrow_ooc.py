"""Out-of-core tree growth: chunked histogram accumulation over a
streamed binned matrix (docs round 12 — the spill regime of the
``out_of_core=`` data path).

The in-memory growers take the whole (N, F) binned matrix as one traced
device input, which is exactly what a dataset LARGER THAN HBM cannot
provide.  This grower keeps only the O(N) vectors on device — leaf ids,
gradients, hessians, masks — plus the O(L*F*B) histogram state, and
streams the binned matrix through the device in fixed-shape row chunks
(io/stream.py: pinned reused host buffers, one-deep upload prefetch) once
per histogram pass.  The matrix itself is never device-resident.

Exactness contract (pinned by tests/test_out_of_core.py): the grower is
a chunk-streamed mirror of the STRICT grower (ops/treegrow.py grow_tree,
serial mode) with the scatter histogram strategy.  Two facts make the
mirror bitwise, not approximately, equal:

* the per-leaf masked scatter histogram is an order-preserving fold —
  seeding each chunk's scatter-add with the running accumulator
  continues the SAME row-order addition chain the one-shot scatter
  performs, so any chunk partition (1 row, odd sizes, powers of two,
  all-N) produces bit-identical histograms;
* every other per-split computation (split search, leaf bookkeeping,
  partition decisions) is either O(L)/O(F) device math reusing the very
  same functions (``find_best_split``, ``leaf_output``) or an
  elementwise per-row update whose chunking cannot reorder anything.

Bitwise parity with IN-MEMORY training therefore holds whenever the
in-memory grower also selects the scatter strategy — max_bin > 64 or
> 512 features (ops/histogram.py ``histogram(strategy="auto")``), which
is precisely the wide regime out-of-core exists for.  Narrow-bin
in-memory runs use the one-hot einsum whose reduction tree differs in
ulps; the models are statistically indistinguishable but not bit-equal,
and the tests pin the scatter regime only.

Envelope (gated in models/gbdt.py): serial single-device, numerical +
categorical splits, bagging/GOSS row masks, feature_fraction, max_depth.
No monotone/interaction/forced splits, CEGB, linear leaves or
extra_trees — configurations outside the envelope raise at setup rather
than silently training something else.

Dispatch/sync shape (honest): this is a host-driven per-split loop —
one small blocking pull per split for the can-split decision (the strict
grower's host analogue) plus ``ceil(N/chunk)`` chunk dispatches per
pass.  The windowed 1-dispatch/0-sync budget applies to the RESIDENT
out-of-core regime (standard growers over a stream-assembled device
matrix), not to spill-mode growth; tests/test_out_of_core.py pins both.
The chunk steps' IR is pinned by the ``ooc_root_chunk`` /
``ooc_split_chunk`` audit contracts (analysis/contracts.py): donated
accumulators consumable, collective/callback/transfer-free bodies,
bounded live set (docs/ANALYSIS.md "Jaxpr audit layer").
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..obs import metrics as _obs
from ..utils import sanitizer as _san
from .split import (BestSplit, SplitParams, find_best_split, leaf_output,
                    leaf_output_smoothed, KMIN_SCORE)
from .treegrow import TreeArrays, _empty_best, _set_best


class OocState(NamedTuple):
    hist: jnp.ndarray  # (L, 3, F, B) f32
    best: BestSplit
    leaf_sum_g: jnp.ndarray  # (L,)
    leaf_sum_h: jnp.ndarray
    leaf_count: jnp.ndarray
    leaf_depth: jnp.ndarray
    leaf_parent: jnp.ndarray
    leaf_side: jnp.ndarray
    num_leaves_cur: jnp.ndarray
    leaf_out: jnp.ndarray
    tree: TreeArrays


def _slice_rows(vec, row_lo, c: int):
    return jax.lax.dynamic_slice_in_dim(vec, row_lo, c, axis=0)


@functools.partial(jax.jit, static_argnames=("num_bins",), donate_argnums=(0,))
def _hist_chunk_update(
    hist,  # (3, F, B) f32 — running accumulator (donated)
    chunk_bins,  # (C, F) int — fixed-shape padded chunk
    mask,  # (C,) f32 — leaf-membership x row_mask weights (0.0 on pads)
    grad_c,  # (C,) f32 — sample-weighted, sliced from the resident vector
    hess_c,  # (C,) f32
    valid,  # (C,) bool — False on the padded tail
    *,
    num_bins: int,
):
    """Seed-and-continue masked scatter: bit-for-bit the next chunk of the
    one-shot ``histogram_scatter`` fold (module docstring).  PAD rows
    route to an out-of-range index and are dropped entirely — a padded
    row must contribute NOTHING, not even a +0.0 that could flip a -0.0
    accumulator bit (in-memory rows, masked or not, all scatter)."""
    c, f = chunk_bins.shape
    payload = jnp.stack([grad_c * mask, hess_c * mask, mask], axis=0)
    payload = jnp.broadcast_to(payload[:, :, None], (3, c, f))
    flat = chunk_bins.astype(jnp.int32) + (
        jnp.arange(f, dtype=jnp.int32) * num_bins)[None, :]
    flat = jnp.where(valid[:, None], flat, f * num_bins)
    hf = hist.reshape(3, f * num_bins)
    return hf.at[:, flat].add(payload, mode="drop").reshape(hist.shape)


@functools.partial(jax.jit, static_argnames=("num_bins",), donate_argnums=(0,))
def _root_chunk_step(
    hist,  # (3, F, B) f32 — running accumulator (donated)
    chunk_bins,  # (C, F) int
    row_lo,  # i32 scalar (traced)
    valid,  # (C,) bool
    grad_pad,  # (Np,) f32 resident (sample-weighted)
    hess_pad,  # (Np,) f32
    row_mask_pad,  # (Np,) bool
    *,
    num_bins: int,
):
    """One chunk of the root pass: the leaf-0 membership mask and the
    resident-vector slices happen INSIDE the jit, so the sweep costs
    exactly the one accounted dispatch per chunk the module docstring
    promises (no eager mask/slice round-trips in the host hot loop)."""
    c = chunk_bins.shape[0]
    mask = (_slice_rows(row_mask_pad, row_lo, c) & valid).astype(jnp.float32)
    hist = _hist_chunk_update(
        hist, chunk_bins, mask,
        _slice_rows(grad_pad, row_lo, c), _slice_rows(hess_pad, row_lo, c),
        valid, num_bins=num_bins)
    return hist


@functools.partial(jax.jit, static_argnames=("num_bins",),
                   donate_argnums=(0, 1))
def _split_chunk_step(
    leaf_id_pad,  # (Np,) i32 — resident, donated
    hist_small,  # (3, F, B) f32 — the small child's accumulator, donated
    chunk_bins,  # (C, F) int
    row_lo,  # i32 scalar (traced)
    valid,  # (C,) bool
    grad_pad,  # (Np,) f32 resident (sample-weighted)
    hess_pad,  # (Np,) f32
    row_mask_pad,  # (Np,) bool
    missing_bin_pf,  # (F,) i32
    sel,  # dict of traced split scalars (see grow_tree_ooc)
    *,
    num_bins: int,
):
    """One chunk of a split's fused partition + small-child histogram
    sweep: update the chunk's leaf ids by the split decision, then fold
    the chunk's small-child rows into the histogram accumulator.  The
    partition is elementwise (chunking changes nothing); the histogram
    is the seeded fold (bitwise, module docstring)."""
    c = chunk_bins.shape[0]
    lid = _slice_rows(leaf_id_pad, row_lo, c)
    fcol = jnp.take_along_axis(
        chunk_bins.astype(jnp.int32),
        jnp.broadcast_to(sel["feature"], (c,))[:, None], axis=1)[:, 0]
    is_missing = fcol == missing_bin_pf[sel["feature"]]
    go_left_num = jnp.where(is_missing, sel["default_left"],
                            fcol <= sel["threshold_bin"])
    go_left = jnp.where(sel["is_cat"], sel["cat_mask"][fcol], go_left_num)
    in_leaf = lid == sel["best_leaf"]
    new_lid = jnp.where(in_leaf & ~go_left & valid, sel["new_leaf"], lid)
    leaf_id_pad = jax.lax.dynamic_update_slice(leaf_id_pad, new_lid, (row_lo,))

    mask_small = ((new_lid == sel["small_leaf"])
                  & _slice_rows(row_mask_pad, row_lo, c)).astype(jnp.float32)
    hist_small = _hist_chunk_update(
        hist_small, chunk_bins, mask_small,
        _slice_rows(grad_pad, row_lo, c), _slice_rows(hess_pad, row_lo, c),
        valid, num_bins=num_bins)
    return leaf_id_pad, hist_small


@jax.jit
def _select_split(best: BestSplit, num_leaves_cur):
    """The winning leaf's split scalars (device, no pull) — mirrors the
    strict grower's ``do_split`` selection."""
    best_leaf = jnp.argmax(best.gain).astype(jnp.int32)
    s = jax.tree.map(lambda a: a[best_leaf], best)
    left_smaller = s.left_count <= s.right_count
    return {
        "best_leaf": best_leaf,
        "feature": s.feature,
        "threshold_bin": s.threshold_bin,
        "default_left": s.default_left,
        "is_cat": s.is_cat,
        "cat_mask": s.cat_mask,
        "new_leaf": num_leaves_cur,
        "small_leaf": jnp.where(left_smaller, best_leaf, num_leaves_cur),
    }


def _best_for(hist_leaf, sum_g, sum_h, count, depth, parent_out,
              feature_mask, num_bins_pf, missing_bin_pf, categorical_mask,
              params: SplitParams, max_depth: int):
    """Identical kwargs to the strict grower's serial-mode ``best_for``
    (no monotone/interaction/CEGB/rng — outside the OOC envelope)."""
    s = find_best_split(
        hist_leaf, sum_g, sum_h, count, num_bins_pf, missing_bin_pf,
        params, feature_mask=feature_mask, categorical_mask=categorical_mask,
        out_lo=jnp.float32(-jnp.inf), out_hi=jnp.float32(jnp.inf),
        depth=(depth.astype(jnp.float32) if hasattr(depth, "astype")
               else jnp.float32(depth)),
        parent_output=parent_out,
    )
    if max_depth > 0:
        s = s._replace(gain=jnp.where(depth >= max_depth, KMIN_SCORE, s.gain))
    return s


@functools.partial(jax.jit, static_argnames=("num_leaves", "num_bins",
                                             "max_depth", "params"))
def _root_state(
    hist0, feature_mask, num_bins_pf, missing_bin_pf, categorical_mask,
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int,
    params: SplitParams,
) -> OocState:
    """Root leaf state from the streamed root histogram — the strict
    grower's leaf-0 setup, with the hist handed in instead of computed."""
    L = num_leaves
    f = hist0.shape[1]
    sum0 = jnp.sum(hist0[:, 0, :], axis=1)  # totals from feature 0: (3,)
    g0, h0, c0 = sum0[0], sum0[1], sum0[2]
    leaf_out0 = leaf_output(g0, h0, params)
    best0 = _set_best(
        _empty_best(L, num_bins), jnp.asarray(0),
        _best_for(hist0, g0, h0, c0, jnp.asarray(0), leaf_out0,
                  feature_mask, num_bins_pf, missing_bin_pf,
                  categorical_mask, params, max_depth))
    tree0 = TreeArrays(
        num_leaves=jnp.asarray(1, jnp.int32),
        split_feature=jnp.zeros((L - 1,), jnp.int32),
        threshold_bin=jnp.zeros((L - 1,), jnp.int32),
        default_left=jnp.zeros((L - 1,), bool),
        split_gain=jnp.zeros((L - 1,), jnp.float32),
        left_child=jnp.zeros((L - 1,), jnp.int32),
        right_child=jnp.zeros((L - 1,), jnp.int32),
        internal_value=jnp.zeros((L - 1,), jnp.float32),
        internal_weight=jnp.zeros((L - 1,), jnp.float32),
        internal_count=jnp.zeros((L - 1,), jnp.float32),
        leaf_value=jnp.zeros((L,), jnp.float32),
        leaf_weight=jnp.zeros((L,), jnp.float32),
        leaf_count=jnp.zeros((L,), jnp.float32),
        leaf_sum_g=jnp.zeros((L,), jnp.float32),
        leaf_depth=jnp.zeros((L,), jnp.int32),
        is_cat=jnp.zeros((L - 1,), bool),
        cat_mask=jnp.zeros((L - 1, num_bins), bool),
    )
    return OocState(
        hist=jnp.zeros((L, 3, f, num_bins), jnp.float32).at[0].set(hist0),
        best=best0,
        leaf_sum_g=jnp.zeros((L,), jnp.float32).at[0].set(g0),
        leaf_sum_h=jnp.zeros((L,), jnp.float32).at[0].set(h0),
        leaf_count=jnp.zeros((L,), jnp.float32).at[0].set(c0),
        leaf_depth=jnp.zeros((L,), jnp.int32),
        leaf_parent=jnp.full((L,), -1, jnp.int32),
        leaf_side=jnp.zeros((L,), jnp.int32),
        num_leaves_cur=jnp.asarray(1, jnp.int32),
        leaf_out=jnp.zeros((L,), jnp.float32).at[0].set(leaf_out0),
        tree=tree0,
    )


@functools.partial(jax.jit, static_argnames=("num_leaves", "num_bins",
                                             "max_depth", "params"),
                   donate_argnums=(0,))
def _finish_split(
    state: OocState,
    hist_small,
    feature_mask, num_bins_pf, missing_bin_pf, categorical_mask,
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int,
    params: SplitParams,
) -> OocState:
    """Post-sweep bookkeeping — a faithful mirror of the strict grower's
    ``do_split`` tail (serial mode, envelope features only)."""
    best_leaf = jnp.argmax(state.best.gain).astype(jnp.int32)
    s = jax.tree.map(lambda a: a[best_leaf], state.best)
    node = state.num_leaves_cur - 1
    new_leaf = state.num_leaves_cur
    left_smaller = s.left_count <= s.right_count

    parent_hist = state.hist[best_leaf]
    hist_big = parent_hist - hist_small
    hist_left = jnp.where(left_smaller, hist_small, hist_big)
    hist_right = jnp.where(left_smaller, hist_big, hist_small)
    hist = state.hist.at[best_leaf].set(hist_left).at[new_leaf].set(hist_right)

    parent_out = state.leaf_out[best_leaf]
    old_parent = state.leaf_parent[best_leaf]
    old_side = state.leaf_side[best_leaf]
    t = state.tree
    lc = jnp.where((old_parent >= 0) & (old_side == 0),
                   t.left_child.at[old_parent].set(node), t.left_child)
    rc = jnp.where((old_parent >= 0) & (old_side == 1),
                   t.right_child.at[old_parent].set(node), t.right_child)
    lc = lc.at[node].set(-best_leaf - 1)
    rc = rc.at[node].set(-new_leaf - 1)
    depth_child = state.leaf_depth[best_leaf] + 1
    tree = t._replace(
        num_leaves=state.num_leaves_cur + 1,
        split_feature=t.split_feature.at[node].set(s.feature),
        threshold_bin=t.threshold_bin.at[node].set(s.threshold_bin),
        default_left=t.default_left.at[node].set(s.default_left),
        split_gain=t.split_gain.at[node].set(s.gain),
        left_child=lc,
        right_child=rc,
        internal_value=t.internal_value.at[node].set(parent_out),
        internal_weight=t.internal_weight.at[node].set(
            state.leaf_sum_h[best_leaf]),
        internal_count=t.internal_count.at[node].set(
            state.leaf_count[best_leaf]),
        is_cat=t.is_cat.at[node].set(s.is_cat),
        cat_mask=t.cat_mask.at[node].set(s.cat_mask),
    )

    leaf_sum_g = state.leaf_sum_g.at[best_leaf].set(
        s.left_sum_g).at[new_leaf].set(s.right_sum_g)
    leaf_sum_h = state.leaf_sum_h.at[best_leaf].set(
        s.left_sum_h).at[new_leaf].set(s.right_sum_h)
    leaf_count = state.leaf_count.at[best_leaf].set(
        s.left_count).at[new_leaf].set(s.right_count)
    leaf_depth = state.leaf_depth.at[best_leaf].set(
        depth_child).at[new_leaf].set(depth_child)
    leaf_parent = state.leaf_parent.at[best_leaf].set(
        node).at[new_leaf].set(node)
    leaf_side = state.leaf_side.at[best_leaf].set(0).at[new_leaf].set(1)

    out_l_c = leaf_output_smoothed(s.left_sum_g, s.left_sum_h, s.left_count,
                                   parent_out, params)
    out_r_c = leaf_output_smoothed(s.right_sum_g, s.right_sum_h,
                                   s.right_count, parent_out, params)
    leaf_out = state.leaf_out.at[best_leaf].set(out_l_c).at[new_leaf].set(
        out_r_c)

    bl = _best_for(hist_left, s.left_sum_g, s.left_sum_h, s.left_count,
                   depth_child, out_l_c, feature_mask, num_bins_pf,
                   missing_bin_pf, categorical_mask, params, max_depth)
    br = _best_for(hist_right, s.right_sum_g, s.right_sum_h, s.right_count,
                   depth_child, out_r_c, feature_mask, num_bins_pf,
                   missing_bin_pf, categorical_mask, params, max_depth)
    best = _set_best(_set_best(state.best, best_leaf, bl), new_leaf, br)

    return OocState(
        hist=hist, best=best, leaf_sum_g=leaf_sum_g, leaf_sum_h=leaf_sum_h,
        leaf_count=leaf_count, leaf_depth=leaf_depth,
        leaf_parent=leaf_parent, leaf_side=leaf_side,
        num_leaves_cur=state.num_leaves_cur + 1, leaf_out=leaf_out,
        tree=tree,
    )


def grow_tree_ooc(
    chunk_source: Callable,  # () -> iterator of (row_lo, host_chunk)
    n: int,
    f: int,
    grad: jnp.ndarray,  # (N,) f32
    hess: jnp.ndarray,  # (N,) f32
    row_mask: jnp.ndarray,  # (N,) bool
    sample_weight: jnp.ndarray,  # (N,) f32
    feature_mask: jnp.ndarray,  # (F,) bool
    num_bins_pf: jnp.ndarray,
    missing_bin_pf: jnp.ndarray,
    categorical_mask: Optional[jnp.ndarray] = None,
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    chunk_rows: int,
    stats: Optional[dict] = None,
) -> tuple[TreeArrays, jnp.ndarray]:
    """Grow one tree over a streamed binned matrix; returns
    (tree, leaf_id per row) — the strict grower's contract.

    ``chunk_source`` is re-invoked once per histogram pass (1 root pass +
    1 pass per split); each invocation must yield the SAME chunks in the
    same order (io/stream.py sources do).  ``stats``, when given,
    receives {splits, passes, chunks} for the bench/telemetry layer.
    """
    from ..io.stream import prefetch_device

    L = num_leaves
    c_rows = max(int(chunk_rows), 1)
    n_pad = -(-n // c_rows) * c_rows
    statics = dict(num_leaves=L, num_bins=num_bins, max_depth=max_depth,
                   params=params)

    def pad_to(vec, fill):
        return jnp.pad(vec, (0, n_pad - n), constant_values=fill)

    # the sample-weight fold mirrors grow_tree's entry exactly
    grad_pad = pad_to(grad.astype(jnp.float32) * sample_weight, 0)
    hess_pad = pad_to(hess.astype(jnp.float32) * sample_weight, 0)
    row_mask_pad = pad_to(row_mask, False)
    leaf_id_pad = jnp.zeros((n_pad,), jnp.int32)

    passes = chunks_seen = 0

    # valid-tail masks take at most TWO values per sweep (all-True for
    # full chunks, one tail variant) — build each once instead of paying
    # an eager arange+compare round-trip per chunk per pass
    _valid_cache: dict = {}

    def _valid(m: int) -> jnp.ndarray:
        v = _valid_cache.get(m)
        if v is None:
            v = _valid_cache[m] = jnp.arange(c_rows, dtype=jnp.int32) < m
        return v

    # ---- root pass: one streamed sweep builds leaf 0's histogram ----
    hist = jnp.zeros((3, f, num_bins), jnp.float32)
    for row_lo, m, dev in prefetch_device(
            chunk_source(), dtype=jnp.int16, pad_rows=c_rows):
        _san.record_dispatch()
        hist = _root_chunk_step(
            hist, dev, jnp.int32(row_lo), _valid(m),
            grad_pad, hess_pad, row_mask_pad, num_bins=num_bins)
        chunks_seen += 1
    passes += 1
    state = _root_state(hist, feature_mask, num_bins_pf, missing_bin_pf,
                        categorical_mask, **statics)

    # ---- per-split host loop (the strict grower's fori_loop, streamed) ----
    splits = 0
    for _ in range(L - 1):
        # the can-split decision is a REAL host data dependency (the loop
        # must stop when no gain clears the bar) — one small accounted
        # pull per split, the strict grower's host-driven analogue
        gmax = float(_san.sync_pull(jnp.max(state.best.gain)))
        if not gmax > KMIN_SCORE / 2:
            break
        sel = _select_split(state.best, state.num_leaves_cur)
        hist_small = jnp.zeros((3, f, num_bins), jnp.float32)
        for row_lo, m, dev in prefetch_device(
                chunk_source(), dtype=jnp.int16, pad_rows=c_rows):
            _san.record_dispatch()
            leaf_id_pad, hist_small = _split_chunk_step(
                leaf_id_pad, hist_small, dev, jnp.int32(row_lo), _valid(m),
                grad_pad, hess_pad, row_mask_pad, missing_bin_pf, sel,
                num_bins=num_bins)
            chunks_seen += 1
        passes += 1
        splits += 1
        state = _finish_split(state, hist_small, feature_mask, num_bins_pf,
                              missing_bin_pf, categorical_mask, **statics)

    # ---- finalize (mirror of grow_tree's tail, envelope features) ----
    if params.path_smooth > 0:
        leaf_value = state.leaf_out
    else:
        leaf_value = leaf_output(state.leaf_sum_g, state.leaf_sum_h, params)
    active = jnp.arange(L, dtype=jnp.int32) < state.num_leaves_cur
    tree = state.tree._replace(
        num_leaves=state.num_leaves_cur,
        leaf_value=jnp.where(active, leaf_value, 0.0),
        leaf_weight=jnp.where(active, state.leaf_sum_h, 0.0),
        leaf_count=jnp.where(active, state.leaf_count, 0.0),
        leaf_sum_g=jnp.where(active, state.leaf_sum_g, 0.0),
        leaf_depth=state.leaf_depth,
    )
    if stats is not None:
        stats.update(splits=splits, passes=passes, chunks=chunks_seen)
    if _obs.enabled():
        _obs.counter("train_ooc_passes_total").inc(passes)
        _obs.counter("train_ooc_chunks_total").inc(chunks_seen)
    return tree, leaf_id_pad[:n]
