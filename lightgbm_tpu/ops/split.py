"""Split-gain search over histograms, as vectorized XLA reductions.

TPU-native replacement for the reference's per-feature threshold scan
(reference: src/treelearner/feature_histogram.hpp ->
FeatureHistogram::FindBestThreshold / FindBestThresholdSequentially and
src/treelearner/cuda/cuda_best_split_finder.cu).  Where the reference scans
bins left->right and right->left per feature in scalar code, here the whole
(F, B) plane is evaluated at once with cumulative sums, both missing-value
default directions evaluated in parallel, and the argmax taken as a single
XLA reduction — the formulation that maps to the VPU/MXU instead of a loop.

Math (must match reference exactly; SURVEY.md §8):
  ThresholdL1(g, l1) = sign(g) * max(0, |g| - l1)
  leaf_output = -ThresholdL1(G, l1) / (H + l2)        [clipped to max_delta_step]
  leaf_gain   = ThresholdL1(G, l1)^2 / (H + l2)       [x0.5 cancels in deltas]
  split_gain  = gain(L) + gain(R) - gain(parent)
constraints: counts >= min_data_in_leaf, hess >= min_sum_hessian_in_leaf,
             split_gain > min_gain_to_split.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

KEPSILON = 1e-15  # reference: feature_histogram.hpp kEpsilon added to hessians
KMIN_SCORE = -1e30


class SplitParams(NamedTuple):
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    path_smooth: float = 0.0
    # categorical split params (reference: FindBestThresholdCategoricalInner)
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    # node-level sampling (reference: ColSampler bynode / extra_trees)
    feature_fraction_bynode: float = 1.0
    extra_trees: bool = False
    # monotone split gain penalty (reference: config monotone_penalty ->
    # ComputeMonotoneSplitGainPenalty in monotone_constraints.hpp)
    monotone_penalty: float = 0.0
    # CEGB (reference: src/treelearner/cost_effective_gradient_boosting.hpp):
    # split gain is charged cegb_tradeoff * cegb_penalty_split * num_data
    # plus per-feature penalties (passed per-leaf via cegb_feature_penalty)
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0


class BestSplit(NamedTuple):
    """Per-leaf best split description (reference: struct SplitInfo in
    src/treelearner/split_info.hpp — incl. its cat_threshold bitset, here a
    dense (B,) bool mask over bins that go LEFT)."""

    gain: jnp.ndarray  # f32
    feature: jnp.ndarray  # i32
    threshold_bin: jnp.ndarray  # i32 (bin <= threshold_bin -> left)
    default_left: jnp.ndarray  # bool (missing goes left)
    is_cat: jnp.ndarray  # bool — categorical (bitmask) split
    cat_mask: jnp.ndarray  # (B,) bool — bins going left (categorical only)
    left_sum_g: jnp.ndarray
    left_sum_h: jnp.ndarray
    left_count: jnp.ndarray
    right_sum_g: jnp.ndarray
    right_sum_h: jnp.ndarray
    right_count: jnp.ndarray


def threshold_l1(g: jnp.ndarray, l1: jnp.ndarray) -> jnp.ndarray:
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def leaf_output(sum_g, sum_h, p: SplitParams):
    """reference: FeatureHistogram::CalculateSplittedLeafOutput."""
    out = -threshold_l1(sum_g, p.lambda_l1) / (sum_h + p.lambda_l2 + KEPSILON)
    if p.max_delta_step > 0:
        out = jnp.clip(out, -p.max_delta_step, p.max_delta_step)
    return out


def leaf_output_smoothed(sum_g, sum_h, count, parent_output, p: SplitParams):
    """Path-smoothed leaf output (reference: CalculateSplittedLeafOutput with
    USE_SMOOTHING: ret = raw * n/(n+smooth) + parent_output * smooth/(n+smooth),
    written there as (n/s)/(n/s + 1) with s = path_smooth)."""
    raw = leaf_output(sum_g, sum_h, p)
    if p.path_smooth <= 0:
        return raw
    alpha = count / (count + p.path_smooth)
    return raw * alpha + parent_output * (1.0 - alpha)


def gain_given_output(sum_g, sum_h, l1, l2, out):
    """reference: GetLeafGainGivenOutput (x-0.5 factor dropped as elsewhere)."""
    tg = threshold_l1(sum_g, l1)
    return -(2.0 * tg * out + (sum_h + l2 + KEPSILON) * out * out)


def monotone_split_gain_penalty(depth, penalization):
    """reference: LeafConstraintsBase::ComputeMonotoneSplitGainPenalty —
    forbids monotone splits on the first floor(penalization) levels and
    continuously penalizes beyond (returns the multiplicative factor)."""
    depth = depth.astype(jnp.float32) if hasattr(depth, "astype") else jnp.float32(depth)
    eps = 1e-10
    full = penalization >= depth + 1.0
    small = penalization <= 1.0
    f_small = 1.0 - penalization / jnp.exp2(depth) + eps
    f_big = 1.0 - jnp.exp2(penalization - 1.0 - depth) + eps
    return jnp.where(full, eps, jnp.where(small, f_small, f_big))


def leaf_gain(sum_g, sum_h, p: SplitParams):
    """reference: GetLeafGain in feature_histogram.hpp (0.5 factor dropped —
    it cancels in gain deltas; reference keeps it, so model-format split_gain
    values are written with the 0.5 applied at serialization time)."""
    tg = threshold_l1(sum_g, p.lambda_l1)
    denom = sum_h + p.lambda_l2 + KEPSILON
    if p.max_delta_step > 0:
        # with output clipping the gain must be evaluated at the clipped output
        # (reference: GetLeafGainGivenOutput)
        out = jnp.clip(-tg / denom, -p.max_delta_step, p.max_delta_step)
        return -(2.0 * tg * out + denom * out * out)
    return tg * tg / denom


def _gain_l2(sum_g, sum_h, l1, l2, max_delta_step):
    """leaf_gain with explicit regularizers (categorical adds cat_l2)."""
    tg = threshold_l1(sum_g, l1)
    denom = sum_h + l2 + KEPSILON
    if max_delta_step > 0:
        out = jnp.clip(-tg / denom, -max_delta_step, max_delta_step)
        return -(2.0 * tg * out + denom * out * out)
    return tg * tg / denom


def gain_plane(
    hist: jnp.ndarray,  # (3, F, B) f32 — per-feature histograms for ONE leaf
    # (channel-first: the minor (F, B) tile pair lays out pad-free on TPU)
    parent_sum_g: jnp.ndarray,
    parent_sum_h: jnp.ndarray,
    parent_count: jnp.ndarray,
    num_bins_per_feature: jnp.ndarray,  # (F,) i32 total bins incl. missing slot
    missing_bin_per_feature: jnp.ndarray,  # (F,) i32; -1 if feature has no NaN bin
    params: SplitParams,
    feature_mask: jnp.ndarray | None = None,  # (F,) bool — col sampling / constraints
    categorical_mask: jnp.ndarray | None = None,  # (F,) bool — categorical features
    monotone_constraints: jnp.ndarray | None = None,  # (F,) i32 in {-1,0,1}
    out_lo: jnp.ndarray | None = None,  # scalar — leaf output lower bound
    out_hi: jnp.ndarray | None = None,  # scalar — leaf output upper bound
    rng_key: jnp.ndarray | None = None,  # per-node key (extra_trees / bynode)
    depth: jnp.ndarray | None = None,  # scalar — leaf depth (monotone_penalty)
    parent_output: jnp.ndarray | None = None,  # scalar — this leaf's output (path_smooth)
    cegb_feature_penalty: jnp.ndarray | None = None,  # (F,) pre-scaled coupled penalty
    feature_contri: jnp.ndarray | None = None,  # (F,) split-gain multipliers
):
    """Evaluate every (feature, threshold, missing-direction) candidate and
    return `(gain (F, B), ctx)` — the full candidate-gain plane plus the
    context needed to materialize the winner (select_from_plane).  Split out
    from the selection so the voting-parallel learner can vote on per-feature
    local gains (reference: VotingParallelTreeLearner's local SplitInfo ranks).

    Numerical split semantics: rows with bin <= t go left; missing rows go to
    the default direction.  Missing bin sits at index (num_bins-1) when
    present (binning.py), and is excluded from the cumulative scan.
    """
    _, f, b = hist.shape
    bins_idx = jnp.arange(b, dtype=jnp.int32)

    # zero-out the missing bin from the scan; keep its mass separately
    has_missing = missing_bin_per_feature >= 0  # (F,)
    is_missing_bin = bins_idx[None, :] == missing_bin_per_feature[:, None]  # (F, B)
    hist_nm = jnp.where(is_missing_bin[None], 0.0, hist)  # (3, F, B)
    miss = jnp.sum(jnp.where(is_missing_bin[None], hist, 0.0), axis=2)  # (3, F)

    cum = jnp.cumsum(hist_nm, axis=2)  # (3, F, B) left stats at threshold=b

    # candidate validity: threshold t splits between bin t and t+1; the last
    # non-missing bin cannot be a threshold.
    last_nm_bin = num_bins_per_feature - jnp.where(has_missing, 2, 1)  # index of last non-missing bin

    # node-level feature sampling (reference: ColSampler::GetByNode) and
    # extra_trees' single random threshold per feature (ExtraTreeLearner-like
    # mode folded into the scan by masking candidates)
    if rng_key is not None:
        k_bynode, k_extra = jax.random.split(rng_key)
        if params.feature_fraction_bynode < 1.0:
            keep = jax.random.uniform(k_bynode, (f,)) < params.feature_fraction_bynode
            feature_mask = keep if feature_mask is None else (feature_mask & keep)

    valid_thr = bins_idx[None, :] < last_nm_bin[:, None]  # (F, B)
    if rng_key is not None and params.extra_trees:
        rbin = jnp.floor(
            jax.random.uniform(k_extra, (f,)) * jnp.maximum(last_nm_bin, 1)
        ).astype(jnp.int32)
        valid_thr = valid_thr & (bins_idx[None, :] == rbin[:, None])
    if feature_mask is not None:
        valid_thr = valid_thr & feature_mask[:, None]

    parent_g = parent_sum_g
    parent_h = parent_sum_h
    use_smooth = params.path_smooth > 0 and parent_output is not None
    if use_smooth:
        # with path smoothing all gains are evaluated at actual (smoothed)
        # outputs; the parent term uses the leaf's stored output
        # (reference: the USE_SMOOTHING instantiations of GetSplitGains)
        gain_parent = gain_given_output(
            parent_g, parent_h, params.lambda_l1, params.lambda_l2, parent_output
        )
    else:
        gain_parent = leaf_gain(parent_g, parent_h, params)

    def eval_direction(missing_left: bool):
        add = miss if missing_left else jnp.zeros_like(miss)  # (3, F)
        left_g = cum[0] + add[0][:, None]
        left_h = cum[1] + add[1][:, None]
        left_c = cum[2] + add[2][:, None]
        right_g = parent_g - left_g
        right_h = parent_h - left_h
        right_c = parent_count - left_c
        ok = (
            valid_thr
            & (left_c >= params.min_data_in_leaf)
            & (right_c >= params.min_data_in_leaf)
            & (left_h >= params.min_sum_hessian_in_leaf)
            & (right_h >= params.min_sum_hessian_in_leaf)
        )
        if monotone_constraints is None and not use_smooth:
            g = leaf_gain(left_g, left_h, params) + leaf_gain(right_g, right_h, params) - gain_parent
        else:
            # output-based gains: smoothing shrinks child outputs towards
            # the parent's; the basic monotone method additionally clips to
            # the inherited [out_lo, out_hi] band and rejects ordering
            # violations (reference: monotone_constraints.hpp
            # BasicLeafConstraints + GetSplitGainGivenOutput).
            if use_smooth:
                out_l = leaf_output_smoothed(left_g, left_h, left_c, parent_output, params)
                out_r = leaf_output_smoothed(right_g, right_h, right_c, parent_output, params)
            else:
                out_l = leaf_output(left_g, left_h, params)
                out_r = leaf_output(right_g, right_h, params)
            if monotone_constraints is not None:
                lo = jnp.float32(-jnp.inf) if out_lo is None else out_lo
                hi = jnp.float32(jnp.inf) if out_hi is None else out_hi
                out_l = jnp.clip(out_l, lo, hi)
                out_r = jnp.clip(out_r, lo, hi)
            g = (
                gain_given_output(left_g, left_h, params.lambda_l1, params.lambda_l2, out_l)
                + gain_given_output(right_g, right_h, params.lambda_l1, params.lambda_l2, out_r)
                - gain_parent
            )
            if monotone_constraints is not None:
                mono = monotone_constraints[:, None]
                viol = ((mono > 0) & (out_l > out_r)) | ((mono < 0) & (out_l < out_r))
                # a leaf whose [lo, hi] band has gone EMPTY (stacked
                # constraints from different monotone ancestors can
                # conflict as bounds evolve) is unsplittable: any child
                # output would breach one of the ancestors.  clip() above
                # silently returns hi in that case, so gate explicitly.
                ok = ok & ~viol & (lo <= hi)
        g = jnp.where(ok, g, KMIN_SCORE)
        return g, (left_g, left_h, left_c)

    gain_r, stats_r = eval_direction(False)  # missing -> right
    gain_l, stats_l = eval_direction(True)  # missing -> left
    # where the feature has no missing values the two directions tie; prefer
    # missing->right to mirror the reference's default (default_left=false
    # when there is nothing to route).
    use_left = gain_l > gain_r
    gain = jnp.where(use_left, gain_l, gain_r)  # (F, B)

    if categorical_mask is not None:
        gain = jnp.where(categorical_mask[:, None], KMIN_SCORE, gain)

    # ------------------------------------------------------------------
    # Categorical candidates (reference: feature_histogram.hpp ->
    # FindBestThresholdCategoricalInner).  Two families:
    #   one-hot   (<= max_cat_to_onehot used bins): each bin alone vs rest;
    #   many-vs-many: bins sorted by sum_g/(sum_h+cat_smooth), prefix of the
    #     sorted order (scanned from both ends, bounded by max_cat_threshold)
    #     goes left.  cat_l2 is added to lambda_l2 in the gain.
    # The missing bin is excluded from left subsets (NaN/unseen -> right),
    # matching Tree::CategoricalDecision's not-in-bitset => right.
    # ------------------------------------------------------------------
    if categorical_mask is not None:
        l2c = params.lambda_l2 + params.cat_l2

        def cgain(g_, h_):
            return _gain_l2(g_, h_, params.lambda_l1, l2c, params.max_delta_step)

        gain_parent_cat = cgain(parent_g, parent_h)
        used = (hist_nm[2] > 0) & ~is_missing_bin  # (F, B)
        num_used = jnp.sum(used, axis=1)  # (F,)
        ratio = jnp.where(
            used,
            hist_nm[0] / (hist_nm[1] + params.cat_smooth),
            jnp.inf,
        )

        def cat_ok(l_c, r_c, l_h, r_h):
            return (
                (l_c >= params.min_data_in_leaf)
                & (r_c >= params.min_data_in_leaf)
                & (l_h >= params.min_sum_hessian_in_leaf)
                & (r_h >= params.min_sum_hessian_in_leaf)
            )

        def eval_sorted(keys):
            order = jnp.argsort(keys, axis=1)  # (F, B) bin ids, unused last
            rank = jnp.argsort(order, axis=1)  # rank of each bin in the order
            sh = jnp.take_along_axis(hist_nm, order[None], axis=2)  # (3, F, B)
            cum = jnp.cumsum(sh, axis=2)  # prefix stats; index k-1 = prefix len k
            k_len = bins_idx[None, :] + 1  # (1, B) prefix length at index b
            lg_, lh_, lc_ = cum[0], cum[1], cum[2]
            rg_, rh_, rc_ = parent_g - lg_, parent_h - lh_, parent_count - lc_
            # reference additionally caps each scan direction at half the
            # used bins ((used_bin + 1) / 2 in
            # FindBestThresholdCategoricalInner), so both-direction scans
            # never consider the same partition twice.
            ok = (
                (k_len <= params.max_cat_threshold)
                & (k_len <= (num_used[:, None] + 1) // 2)
                & (k_len < num_used[:, None])
                & cat_ok(lc_, rc_, lh_, rh_)
            )
            g_ = cgain(lg_, lh_) + cgain(rg_, rh_) - gain_parent_cat
            g_ = jnp.where(ok, g_, KMIN_SCORE)
            return g_, rank, (lg_, lh_, lc_)

        gain_asc, rank_asc, st_asc = eval_sorted(ratio)
        gain_desc, rank_desc, st_desc = eval_sorted(
            jnp.where(used, -ratio, jnp.inf)
        )
        # one-hot: bin b alone goes left
        oh_l = hist_nm  # (3, F, B)
        oh_ok = (
            used
            & cat_ok(
                oh_l[2], parent_count - oh_l[2],
                oh_l[1], parent_h - oh_l[1],
            )
        )
        gain_oh = (
            cgain(oh_l[0], oh_l[1])
            + cgain(parent_g - oh_l[0], parent_h - oh_l[1])
            - gain_parent_cat
        )
        gain_oh = jnp.where(oh_ok, gain_oh, KMIN_SCORE)

        onehot_mode = (num_used <= params.max_cat_to_onehot)[:, None]  # (F, 1)
        gain_mvm = jnp.maximum(gain_asc, gain_desc)
        variant_mvm = jnp.where(gain_desc > gain_asc, 2, 1)
        gain_cat = jnp.where(onehot_mode, gain_oh, gain_mvm)
        variant = jnp.where(onehot_mode, 0, variant_mvm)  # (F, B)
        cat_col = categorical_mask[:, None]
        if feature_mask is not None:
            cat_col = cat_col & feature_mask[:, None]
        gain = jnp.where(cat_col, gain_cat, gain)

    # ------------------------------------------------------------------
    # gain adjustments applied BEFORE the min_gain_to_split gate, matching
    # the reference's ordering (penalized gain must beat min_gain_shift)
    # ------------------------------------------------------------------
    live = gain > KMIN_SCORE / 2
    if (
        params.monotone_penalty > 0
        and monotone_constraints is not None
        and depth is not None
    ):
        factor = monotone_split_gain_penalty(depth, params.monotone_penalty)
        is_mono = (monotone_constraints != 0)[:, None]
        gain = jnp.where(live & is_mono, gain * factor, gain)
    # ordering mirrors the reference: the min_gain gate sees RAW gains
    # (FindBestThresholdSequentially's min_gain_shift), then the chosen
    # gain is scaled by feature_contri (output->gain *= penalty) and the
    # CEGB delta is subtracted (SerialTreeLearner after FindBestThreshold);
    # an adjusted gain must stay positive to produce a split
    gate = live & (gain > params.min_gain_to_split)
    gain = jnp.where(gate, gain, KMIN_SCORE)
    has_adjust = False
    if feature_contri is not None:
        # reference: config feature_contri — gain[i] = max(0, contri[i]) * gain[i]
        contri = jnp.maximum(feature_contri.astype(jnp.float32), 0.0)
        gain = jnp.where(gate, gain * contri[:, None], gain)
        has_adjust = True
    if params.cegb_penalty_split > 0 or cegb_feature_penalty is not None:
        pen = jnp.zeros((f,), jnp.float32)
        if params.cegb_penalty_split > 0:
            pen = pen + params.cegb_tradeoff * params.cegb_penalty_split * parent_count
        if cegb_feature_penalty is not None:
            pen = pen + cegb_feature_penalty
        gain = jnp.where(gate, gain - pen[:, None], gain)
        has_adjust = True
    if has_adjust:
        gain = jnp.where(gate & (gain > 0), gain, KMIN_SCORE)

    ctx = dict(
        use_left=use_left,
        stats_l=stats_l,
        stats_r=stats_r,
        parent_g=parent_g,
        parent_h=parent_h,
        parent_count=parent_count,
        categorical_mask=categorical_mask,
    )
    if categorical_mask is not None:
        ctx.update(
            variant=variant, rank_asc=rank_asc, rank_desc=rank_desc,
            st_asc=st_asc, st_desc=st_desc, oh_l=oh_l,
        )
    return gain, ctx


def select_from_plane(gain: jnp.ndarray, ctx: dict) -> BestSplit:
    """Materialize the argmax candidate of a gain plane into a BestSplit."""
    f, b = gain.shape
    bins_idx = jnp.arange(b, dtype=jnp.int32)
    use_left = ctx["use_left"]
    stats_l, stats_r = ctx["stats_l"], ctx["stats_r"]
    parent_g, parent_h, parent_count = (
        ctx["parent_g"], ctx["parent_h"], ctx["parent_count"]
    )
    categorical_mask = ctx["categorical_mask"]

    flat = gain.reshape(-1)
    best = jnp.argmax(flat)
    best_gain = flat[best]
    best_f = (best // b).astype(jnp.int32)
    best_t = (best % b).astype(jnp.int32)
    best_left = use_left.reshape(-1)[best]

    def pick(sl, sr):
        return jnp.where(best_left, sl.reshape(-1)[best], sr.reshape(-1)[best])

    lg = pick(stats_l[0], stats_r[0])
    lh = pick(stats_l[1], stats_r[1])
    lc = pick(stats_l[2], stats_r[2])
    best_is_cat = jnp.asarray(False)
    best_cat_mask = jnp.zeros((b,), dtype=bool)

    if categorical_mask is not None:
        variant, rank_asc, rank_desc = ctx["variant"], ctx["rank_asc"], ctx["rank_desc"]
        st_asc, st_desc, oh_l = ctx["st_asc"], ctx["st_desc"], ctx["oh_l"]
        best_is_cat = categorical_mask[best_f]
        v = variant.reshape(-1)[best]
        mask_oh = bins_idx == best_t
        mask_asc = rank_asc[best_f] <= best_t
        mask_desc = rank_desc[best_f] <= best_t
        best_cat_mask = jnp.where(
            best_is_cat,
            jnp.where(v == 0, mask_oh, jnp.where(v == 1, mask_asc, mask_desc)),
            jnp.zeros((b,), bool),
        )

        def pick_cat():
            stats = [
                (oh_l[0], oh_l[1], oh_l[2]),
                st_asc,
                st_desc,
            ]
            g_ = jnp.stack([s[0].reshape(-1)[best] for s in stats])[v]
            h_ = jnp.stack([s[1].reshape(-1)[best] for s in stats])[v]
            c_ = jnp.stack([s[2].reshape(-1)[best] for s in stats])[v]
            return g_, h_, c_

        cg, ch, cc = pick_cat()
        lg = jnp.where(best_is_cat, cg, lg)
        lh = jnp.where(best_is_cat, ch, lh)
        lc = jnp.where(best_is_cat, cc, lc)
        best_left = jnp.where(best_is_cat, False, best_left)

    return BestSplit(
        gain=best_gain,
        feature=best_f,
        threshold_bin=best_t,
        default_left=best_left,
        is_cat=best_is_cat,
        cat_mask=best_cat_mask,
        left_sum_g=lg,
        left_sum_h=lh,
        left_count=lc,
        right_sum_g=parent_g - lg,
        right_sum_h=parent_h - lh,
        right_count=parent_count - lc,
    )


class FeatureBests(NamedTuple):
    """Per-FEATURE reduction of a gain plane: for every feature, the best
    threshold's gain and the context needed to materialize a BestSplit if
    that feature wins the cross-feature argmax.  This is the round
    megakernel's on-core output shape (ops/round_pallas.py): reducing the
    (F, B) plane to (F,) per candidate happens while the candidate
    histograms are still VMEM-resident, so the split-gain scan never
    re-reads them from HBM; :func:`select_from_feature_best` finishes the
    O(F) selection outside the kernel.

    Selecting per-feature-first is BITWISE equivalent to
    :func:`select_from_plane`'s flat argmax: both resolve ties to the
    lexicographically first (feature, bin) cell — ``jnp.argmax`` over B
    picks the first maximizing bin per feature, and the cross-feature
    argmax picks the first maximizing feature (pinned by
    tests/test_megakernel.py against find_best_split on tie-heavy
    fixtures, including duplicated columns)."""

    gain: jnp.ndarray  # (F,) f32
    threshold_bin: jnp.ndarray  # (F,) i32
    use_left: jnp.ndarray  # (F,) bool (False on categorical features)
    variant: jnp.ndarray  # (F,) i32: -1 numeric, 0 onehot, 1 asc, 2 desc
    left_g: jnp.ndarray  # (F,) stats of the feature's best candidate
    left_h: jnp.ndarray
    left_c: jnp.ndarray


def reduce_plane_per_feature(gain: jnp.ndarray, ctx: dict) -> FeatureBests:
    """Reduce a gain plane over the bin axis: per feature, the first
    maximizing bin plus the winner-materialization stats
    (:func:`select_from_plane`'s gathers, done per feature instead of at
    the flat argmax cell).  Feature-independent by construction, so the
    megakernel may run it on feature-block slices and concatenate."""
    f, b = gain.shape
    bb = jnp.argmax(gain, axis=1).astype(jnp.int32)  # first max per feature

    def at_bb(x):
        return jnp.take_along_axis(x, bb[:, None], axis=1)[:, 0]

    use_left = at_bb(ctx["use_left"])
    stats_l, stats_r = ctx["stats_l"], ctx["stats_r"]

    def pick(sl, sr):
        return jnp.where(use_left, at_bb(sl), at_bb(sr))

    lg = pick(stats_l[0], stats_r[0])
    lh = pick(stats_l[1], stats_r[1])
    lc = pick(stats_l[2], stats_r[2])
    variant = jnp.full((f,), -1, jnp.int32)
    cmask = ctx["categorical_mask"]
    if cmask is not None:
        v = at_bb(ctx["variant"]).astype(jnp.int32)
        oh_l, st_asc, st_desc = ctx["oh_l"], ctx["st_asc"], ctx["st_desc"]

        def pick_cat(i):
            # mirror select_from_plane's pick_cat: stack the 3 variants'
            # value at the feature's best cell, index by the variant
            stk = jnp.stack([at_bb(oh_l[i]), at_bb(st_asc[i]),
                             at_bb(st_desc[i])])  # (3, F)
            return jnp.take_along_axis(stk, v[None], axis=0)[0]

        lg = jnp.where(cmask, pick_cat(0), lg)
        lh = jnp.where(cmask, pick_cat(1), lh)
        lc = jnp.where(cmask, pick_cat(2), lc)
        use_left = jnp.where(cmask, False, use_left)
        variant = jnp.where(cmask, v, variant)
    return FeatureBests(
        gain=at_bb(gain), threshold_bin=bb, use_left=use_left,
        variant=variant, left_g=lg, left_h=lh, left_c=lc)


def categorical_winner_mask(hist_col: jnp.ndarray, missing_bin, params:
                            SplitParams, variant, threshold) -> jnp.ndarray:
    """Rebuild the winning categorical feature's left-bin mask from its
    (3, B) histogram column — the per-feature rank computation of
    :func:`gain_plane`, replayed for ONE feature.  Deterministic replay of
    the same formulas (same ``hist_nm`` zeroing, same ratio, same stable
    ``argsort``) is bitwise-identical to the plane's rank rows, so the
    megakernel does not need to ship (F, B) rank planes out of the kernel
    to materialize the winner's ``cat_mask``."""
    b = hist_col.shape[1]
    bins_idx = jnp.arange(b, dtype=jnp.int32)
    is_missing = bins_idx == missing_bin
    hist_nm = jnp.where(is_missing[None], 0.0, hist_col)
    used = (hist_nm[2] > 0) & ~is_missing
    ratio = jnp.where(used, hist_nm[0] / (hist_nm[1] + params.cat_smooth),
                      jnp.inf)
    rank_asc = jnp.argsort(jnp.argsort(ratio))
    rank_desc = jnp.argsort(jnp.argsort(jnp.where(used, -ratio, jnp.inf)))
    mask_oh = bins_idx == threshold
    mask_asc = rank_asc <= threshold
    mask_desc = rank_desc <= threshold
    return jnp.where(variant == 0, mask_oh,
                     jnp.where(variant == 1, mask_asc, mask_desc))


def select_from_feature_best(
    fb: FeatureBests,
    parent_g, parent_h, parent_count,
    categorical_mask: jnp.ndarray | None = None,
    cand_hist: jnp.ndarray | None = None,  # (3, F, B) — winner's cat replay
    missing_bin_per_feature: jnp.ndarray | None = None,
    params: SplitParams = SplitParams(),
    num_bins: int | None = None,
) -> BestSplit:
    """Cross-feature half of the split selection: argmax the per-feature
    bests and materialize the winner — the outside-the-kernel counterpart
    of :func:`reduce_plane_per_feature` (bitwise-equal to
    :func:`select_from_plane` on the same plane; see FeatureBests)."""
    best_f = jnp.argmax(fb.gain).astype(jnp.int32)
    best_gain = fb.gain[best_f]
    best_t = fb.threshold_bin[best_f]
    best_left = fb.use_left[best_f]
    b = num_bins if num_bins is not None else (
        cand_hist.shape[2] if cand_hist is not None else 1)
    best_is_cat = jnp.asarray(False)
    best_cat_mask = jnp.zeros((b,), bool)
    if categorical_mask is not None:
        best_is_cat = categorical_mask[best_f]
        best_cat_mask = jnp.where(
            best_is_cat,
            categorical_winner_mask(
                cand_hist[:, best_f], missing_bin_per_feature[best_f],
                params, fb.variant[best_f], best_t),
            jnp.zeros((b,), bool))
    lg, lh, lc = fb.left_g[best_f], fb.left_h[best_f], fb.left_c[best_f]
    return BestSplit(
        gain=best_gain,
        feature=best_f,
        threshold_bin=best_t,
        default_left=best_left,
        is_cat=best_is_cat,
        cat_mask=best_cat_mask,
        left_sum_g=lg,
        left_sum_h=lh,
        left_count=lc,
        right_sum_g=parent_g - lg,
        right_sum_h=parent_h - lh,
        right_count=parent_count - lc,
    )


def find_best_split(
    hist: jnp.ndarray,
    parent_sum_g: jnp.ndarray,
    parent_sum_h: jnp.ndarray,
    parent_count: jnp.ndarray,
    num_bins_per_feature: jnp.ndarray,
    missing_bin_per_feature: jnp.ndarray,
    params: SplitParams,
    feature_mask: jnp.ndarray | None = None,
    categorical_mask: jnp.ndarray | None = None,
    monotone_constraints: jnp.ndarray | None = None,
    out_lo: jnp.ndarray | None = None,
    out_hi: jnp.ndarray | None = None,
    rng_key: jnp.ndarray | None = None,
    depth: jnp.ndarray | None = None,
    parent_output: jnp.ndarray | None = None,
    cegb_feature_penalty: jnp.ndarray | None = None,
    feature_contri: jnp.ndarray | None = None,
) -> BestSplit:
    """gain_plane + select_from_plane (reference: FindBestThreshold)."""
    return _plane_then_select(
        hist, parent_sum_g, parent_sum_h, parent_count,
        num_bins_per_feature, missing_bin_per_feature, params,
        feature_mask, categorical_mask, monotone_constraints, out_lo, out_hi,
        rng_key, depth, parent_output, cegb_feature_penalty, feature_contri,
        cell=None,
    )


def forced_split_candidate(
    hist: jnp.ndarray,  # (3, F, B) — the target leaf's histograms
    parent_sum_g, parent_sum_h, parent_count,
    num_bins_per_feature, missing_bin_per_feature,
    params: SplitParams,
    forced_feature, forced_bin,  # scalars — the scheduled cell
    categorical_mask=None, monotone_constraints=None,
    out_lo=None, out_hi=None, depth=None, parent_output=None,
    feature_contri=None,
) -> BestSplit:
    """Materialize a forced split (reference: SerialTreeLearner::ForceSplits
    — the scheduled (feature, bin) cell is evaluated through the standard
    gain machinery so min_data/min_hess/monotone gates still apply).  Shared
    by the strict and rounds growers; validity = `gain > KMIN_SCORE / 2` on
    the returned split, checked by the caller along with leaf/depth gates."""
    _, f, b = hist.shape
    cell = (
        (jnp.arange(f, dtype=jnp.int32)[:, None] == forced_feature)
        & (jnp.arange(b, dtype=jnp.int32)[None, :] == forced_bin)
    )
    return _plane_then_select(
        hist, parent_sum_g, parent_sum_h, parent_count,
        num_bins_per_feature, missing_bin_per_feature, params,
        None, categorical_mask, monotone_constraints, out_lo, out_hi,
        None, depth, parent_output, None, feature_contri,
        cell=cell,
    )


def _plane_then_select(
    hist, parent_sum_g, parent_sum_h, parent_count,
    num_bins_per_feature, missing_bin_per_feature, params,
    feature_mask, categorical_mask, monotone_constraints, out_lo, out_hi,
    rng_key, depth, parent_output, cegb_feature_penalty, feature_contri,
    cell,
) -> BestSplit:
    gain, ctx = gain_plane(
        hist, parent_sum_g, parent_sum_h, parent_count,
        num_bins_per_feature, missing_bin_per_feature, params,
        feature_mask=feature_mask,
        categorical_mask=categorical_mask,
        monotone_constraints=monotone_constraints,
        out_lo=out_lo,
        out_hi=out_hi,
        rng_key=rng_key,
        depth=depth,
        parent_output=parent_output,
        cegb_feature_penalty=cegb_feature_penalty,
        feature_contri=feature_contri,
    )
    if cell is not None:
        gain = jnp.where(cell, gain, KMIN_SCORE)
    return select_from_plane(gain, ctx)
