"""Round-batched leaf-wise tree growth — the TPU throughput grower.

Motivation (measured on a v5e chip; see ops/hist_pallas.py): one full-data
histogram pass costs ~6 ms at 1M x 28 x 256 regardless of how few rows are
masked in, because the one-hot build is VPU-bound on ALL rows.  The strict
leaf-wise grower (ops/treegrow.py) pays that pass per SPLIT (num_leaves-1
passes/tree).  This grower pays it per ROUND: each round splits EVERY
already-evaluated leaf whose gain clears the bar (best-gain-first within the
remaining num_leaves budget), then computes histograms for ALL new smaller
children in ONE multi-channel Pallas pass (lanes = leaf-slot one-hot x
bf16x2 payload — ops/hist_pallas.py::histogram_pallas_multi), recovers the
bigger siblings by subtraction, and evaluates all fresh leaves with one
vmapped split search.  A 31-leaf tree takes ~6 rounds, not 30 passes.

Semantics vs the reference (src/treelearner/serial_tree_learner.cpp):
identical split math, identical per-leaf histograms; the only deviation is
the growth ORDER — strict best-first splits one leaf at a time and lets a
fresh child compete immediately, while this grower defers fresh children to
the next round.  When the num_leaves budget truncates the final round the
resulting leaf set can differ from the reference's.  This is the same class
of deviation as the reference's own device variants (its CUDA learner
documents minor tree differences vs CPU).  `tree_growth_mode=strict`
(config.py) selects the exact-order grower instead; CPU runs default to
strict, TPU runs to rounds.

Supported here: numerical + categorical splits, missing handling, monotone
(basic AND intermediate — same-round splits under a shared monotone node
are deferred so bound evolution stays sequential, see round_body) +
interaction constraints, max_depth, extra_trees/bynode sampling, CEGB
(split/coupled/lazy per-row charges; lazy is single-device), data-parallel
via shard_map psum (axis_name).  Feature- and voting-parallel modes stay
on the strict grower (their cost is comms-, not pass-, shaped).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..utils import degrade as _degrade
from .histogram import (histogram, histogram_multi, histogram_multi_quantized,
                        histogram_onehot_multi,
                        histogram_onehot_multi_quantized, unbundle_hists)
from .split import (
    BestSplit, SplitParams, find_best_split, forced_split_candidate,
    leaf_output, leaf_output_smoothed, KMIN_SCORE,
)
from .treegrow import TreeArrays, _empty_best, _intermediate_bounds, _set_best


@jax.jit
def predict_leaf_arrays(
    arrays: TreeArrays,
    bins: jnp.ndarray,  # (N, F) int — binned rows (train binner's bin space)
    missing_bin_per_feature: jnp.ndarray,  # (F,) i32
) -> jnp.ndarray:
    """Leaf index per row for a DEVICE tree (fixed-shape vectorized walk;
    host analogue: Tree::GetLeafIndex).  Children encode leaves as ~leaf."""
    n = bins.shape[0]
    L = arrays.leaf_value.shape[0]
    bins = bins.astype(jnp.int32)
    start = jnp.where(arrays.num_leaves > 1, 0, -1).astype(jnp.int32)
    cur0 = jnp.full((n,), 0, jnp.int32) + start

    def body(_, cur):
        is_node = cur >= 0
        nd = jnp.clip(cur, 0, max(L - 2, 0))
        ft = arrays.split_feature[nd]
        col = jnp.take_along_axis(bins, ft[:, None], axis=1)[:, 0]
        miss = col == missing_bin_per_feature[ft]
        gl = jnp.where(miss, arrays.default_left[nd], col <= arrays.threshold_bin[nd])
        gl = jnp.where(arrays.is_cat[nd], arrays.cat_mask[nd, col], gl)
        nxt = jnp.where(gl, arrays.left_child[nd], arrays.right_child[nd])
        return jnp.where(is_node, nxt, cur)

    cur = jax.lax.fori_loop(0, max(L - 1, 1), body, cur0)
    return -cur - 1  # ~cur: node ids exhausted, only leaves remain


class FastState(NamedTuple):
    leaf_id: jnp.ndarray  # (N,) i32
    hist: jnp.ndarray  # (L, 3, F, B) f32 — channel-first: the minor (F, B)
    # tile pair pads ~nothing on TPU, vs 42.7x for a trailing dim of 3
    best: BestSplit  # vectorized over L (gain=KMIN for unevaluated leaves)
    leaf_sum_g: jnp.ndarray  # (L,)
    leaf_sum_h: jnp.ndarray
    leaf_count: jnp.ndarray
    leaf_depth: jnp.ndarray
    leaf_parent: jnp.ndarray
    leaf_side: jnp.ndarray
    num_leaves_cur: jnp.ndarray  # i32
    leaf_out_lo: jnp.ndarray
    leaf_out_hi: jnp.ndarray
    leaf_out: jnp.ndarray  # (L,) f32 — each leaf's (smoothed/clipped) output
    cegb_used: jnp.ndarray  # (F,) bool — features split on in this tree
    used_features: jnp.ndarray  # (L, F) bool or () placeholder
    fresh: jnp.ndarray  # (L,) bool — leaves created this round, need hist+eval
    small_slot: jnp.ndarray  # (L,) i32 — pass slot of each fresh SMALL child, -1 otherwise
    slot_left: jnp.ndarray  # (tile,) i32 — left-child leaf per pass slot (-1
    # inactive).  The parent's hist lives in the LEFT child's state slot
    # (left keeps the parent's leaf id), so the pass can gather parents and
    # do the sibling subtraction on COMPACT (tile,...) arrays instead of
    # the full (L,...) state (measured 57 ms/round of full-state
    # scatter+subtract at Epsilon shape — benchmarks/probe_r5_fixed.py)
    slot_right: jnp.ndarray  # (tile,) i32 — right-child leaf per slot (-1)
    slot_small_left: jnp.ndarray  # (tile,) bool — slot's small child is left
    progress: jnp.ndarray  # bool — this round applied at least one split
    tree: TreeArrays
    anc: jnp.ndarray = False  # (L, L-1) bool ancestor masks, or () placeholder
    aside: jnp.ndarray = False  # (L, L-1) bool — leaf on the RIGHT side of m
    # (maintained only for monotone_method="intermediate"; see treegrow.py)
    lazy_used: jnp.ndarray = False  # (N, F) bool — rows charged per feature
    lazy_counts: jnp.ndarray = False  # (L, F) f32 — per-leaf uncharged rows
    # (maintained only for CEGB cegb_penalty_feature_lazy; reference:
    # CostEfficientGradientBoosting feature_used_in_data bitset)


def _batched_best(
    hist_batch,  # (L, 3, F, B)
    sum_g, sum_h, count,  # (L,)
    num_bins_pf, missing_bin_pf, params,
    feature_mask, categorical_mask, monotone, interaction_sets,
    out_lo, out_hi, used, node_ids, rng_key,
    depth=None, parent_out=None, cegb_pen=None, feature_contri=None,
    lazy_pen=None, lazy_counts=None,  # (F,) penalties x (L, F) uncharged rows
):
    """find_best_split vmapped over leaves."""
    if depth is None:
        depth = jnp.zeros_like(sum_g)
    if parent_out is None:
        parent_out = jnp.zeros_like(sum_g)

    def one(hist, g, h, c, lo, hi, u, nid, dep, pout, lzc):
        fmask = feature_mask
        if interaction_sets is not None and u is not None:
            ok_s = ~jnp.any(u[None, :] & ~interaction_sets, axis=1)
            allowed = jnp.any(interaction_sets & ok_s[:, None], axis=0)
            fmask = allowed if fmask is None else (fmask & allowed)
        key = jax.random.fold_in(rng_key, nid) if rng_key is not None else None
        pen = cegb_pen
        if lzc is not None:
            # CEGB lazy per-row fetch charges: penalty scales with this
            # leaf's uncharged in-bag rows per feature (reference:
            # CostEfficientGradientBoosting::DetailedSplitGain)
            lz = lazy_pen * lzc
            pen = lz if pen is None else pen + lz
        return find_best_split(
            hist, g, h, c, num_bins_pf, missing_bin_pf, params,
            feature_mask=fmask, categorical_mask=categorical_mask,
            monotone_constraints=monotone, out_lo=lo, out_hi=hi, rng_key=key,
            depth=dep.astype(jnp.float32), parent_output=pout,
            cegb_feature_penalty=pen, feature_contri=feature_contri,
        )

    in_axes = (0, 0, 0, 0, 0, 0, 0 if used is not None else None, 0, 0, 0,
               0 if lazy_counts is not None else None)
    return jax.vmap(one, in_axes=in_axes)(
        hist_batch, sum_g, sum_h, count, out_lo, out_hi, used, node_ids,
        depth, parent_out, lazy_counts,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_leaves", "num_bins", "max_depth", "params", "axis_name",
        "leaf_tile", "hist_precision", "use_pallas", "quantize_bins",
        "stochastic_rounding", "quant_renew", "track_path", "n_forced",
        "monotone_method",
    ),
)
def _grow_fast_impl(
    bins: jnp.ndarray,  # (N, F) int
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    row_mask: jnp.ndarray,
    sample_weight: jnp.ndarray,
    feature_mask: jnp.ndarray,
    num_bins_per_feature: jnp.ndarray,
    missing_bin_per_feature: jnp.ndarray,
    categorical_mask: jnp.ndarray = None,
    monotone_constraints: jnp.ndarray = None,
    interaction_sets: jnp.ndarray = None,
    rng_key: jnp.ndarray = None,
    quant_key: jnp.ndarray = None,
    cegb_feature_penalty: jnp.ndarray = None,  # (F,) pre-scaled coupled penalties
    efb_bins: jnp.ndarray = None,  # (N, F_b) bundled bin matrix (io/efb.py)
    efb_gather: jnp.ndarray = None,  # (F, B) int32 into flat (F_b*B)+zero-pad
    efb_default: jnp.ndarray = None,  # (F, B) bool default slots
    bins_t: jnp.ndarray = None,  # (F, N) feature-major copy: partition's
    # per-feature column reads become contiguous row slices (measured:
    # 8 dynamic column slices of (N, F) cost ~1.1 ms/round on v5e)
    feature_contri: jnp.ndarray = None,  # (F,) split-gain multipliers
    forced_leaf: jnp.ndarray = None,  # (K,) i32 — forced-split schedule
    forced_feature: jnp.ndarray = None,  # (K,) i32   (reference: ForceSplits
    forced_bin: jnp.ndarray = None,  # (K,) i32        from forcedsplits JSON)
    cegb_lazy_penalty: jnp.ndarray = None,  # (F,) pre-scaled lazy penalties
    cegb_lazy_used: jnp.ndarray = None,  # (N, F) bool — rows already charged
    *,
    num_leaves: int,
    num_bins: int,
    max_depth: int = -1,
    params: SplitParams = SplitParams(),
    axis_name: Optional[str] = None,
    leaf_tile: int = 16,
    hist_precision: str = "f32",
    use_pallas: bool = True,
    quantize_bins: int = 0,
    stochastic_rounding: bool = True,
    quant_renew: bool = False,
    track_path: bool = False,
    n_forced: int = 0,
    monotone_method: str = "basic",  # basic | intermediate
) -> tuple[TreeArrays, jnp.ndarray]:
    """Grow one tree in rounds; returns (tree, final leaf_id per row).

    quantize_bins > 0 enables quantized training (reference:
    src/treelearner/gradient_discretizer.cpp): gradients/hessians are
    discretized to ints (stochastic rounding), histograms accumulate
    exactly in int32 on the int8 MXU, and split evaluation sees the
    rescaled sums.  quant_renew recomputes leaf outputs from the true f32
    gradients after growth (reference: RenewIntGradTreeOutput).
    """
    n, f = bins.shape
    # bins stay in their storage dtype (int16 on device — half the HBM of
    # int32 at Epsilon scale); kernels and column slices upcast per tile
    grad = grad.astype(jnp.float32) * sample_weight
    hess = hess.astype(jnp.float32) * sample_weight
    grad_true, hess_true = grad, hess
    L = num_leaves

    def psum(x):
        return jax.lax.psum(x, axis_name) if axis_name is not None else x

    if quantize_bins:
        # discretize: grad in [-half, half], hess in [0, quantize_bins]
        # (reference: GradientDiscretizer::DiscretizeGradients)
        half = max(quantize_bins // 2, 1)
        inbag = row_mask.astype(jnp.float32)

        def pmax(x):
            return jax.lax.pmax(x, axis_name) if axis_name is not None else x

        g_scale = jnp.maximum(pmax(jnp.max(jnp.abs(grad) * inbag)) / half, 1e-30)
        h_scale = jnp.maximum(pmax(jnp.max(hess * inbag)) / quantize_bins, 1e-30)
        gs = grad / g_scale
        hs = hess / h_scale
        if stochastic_rounding:
            if quant_key is None:
                quant_key = jax.random.PRNGKey(0)
            kg, kh = jax.random.split(quant_key)
            gq = jnp.floor(gs + jax.random.uniform(kg, gs.shape))
            hq = jnp.floor(hs + jax.random.uniform(kh, hs.shape))
        else:
            gq = jnp.round(gs)
            hq = jnp.round(hs)
        gq = jnp.clip(gq, -127, 127).astype(jnp.int8)
        hq = jnp.clip(hq, 0, 127).astype(jnp.int8)
        # everything downstream sees the dequantized values so leaf stats,
        # subtraction and split eval are consistent with the int histograms
        grad = gq.astype(jnp.float32) * g_scale
        hess = hq.astype(jnp.float32) * h_scale
        quant_scale = jnp.stack([g_scale, h_scale, jnp.float32(1.0)])

    hist_bins = bins if efb_bins is None else efb_bins

    def unbundle(h):
        if efb_gather is None:
            return h
        return unbundle_hists(h, efb_gather, efb_default, f, num_bins)

    def multi_hist(leaf_slot, tile):
        """(N,)-slot -> (tile, 3, F, B) f32: per-slot histograms, one pass."""
        if use_pallas and quantize_bins:
            if num_bins <= 64:
                # same measured strategy selection as the float path: XLA's
                # fused one-hot (here int8 x int8 -> int32) wins at narrow
                # bins; exactness is identical
                hi = histogram_onehot_multi_quantized(
                    hist_bins, gq, hq, row_mask & (leaf_slot >= 0),
                    jnp.maximum(leaf_slot, 0), 0, tile, num_bins,
                )
            else:
                hi = histogram_multi_quantized(
                    hist_bins, gq, hq, row_mask & (leaf_slot >= 0),
                    jnp.maximum(leaf_slot, 0), 0, tile, num_bins,
                )
            h = unbundle(hi).astype(jnp.float32) * quant_scale[:, None, None]
        elif use_pallas and num_bins <= 64:
            # measured strategy selection (ops/histogram.py docstring): at
            # narrow bins XLA's fused one-hot einsum beats the Pallas kernel
            h = histogram_onehot_multi(
                hist_bins, grad, hess, row_mask & (leaf_slot >= 0),
                jnp.maximum(leaf_slot, 0), 0, tile, num_bins,
                precision=hist_precision,
            )
            h = unbundle(h)
        elif use_pallas:
            h = histogram_multi(
                hist_bins, grad, hess, row_mask & (leaf_slot >= 0),
                jnp.maximum(leaf_slot, 0), 0, tile, num_bins,
                precision=hist_precision,
            )
            h = unbundle(h)
        else:
            # CPU/test fallback: per-slot masked scatter histograms (uses the
            # dequantized grad/hess, so results match the int path's scaling)
            def one(s):
                m = row_mask & (leaf_slot == s)
                return histogram(hist_bins, grad, hess, m.astype(jnp.float32),
                                 num_bins, strategy="scatter")
            h = unbundle(jax.vmap(one)(jnp.arange(tile, dtype=jnp.int32)))
        return psum(h)

    # ---- root ----
    hist0 = multi_hist(jnp.where(row_mask, 0, -1).astype(jnp.int32), 1)[0]
    sum0 = jnp.sum(hist0[:, 0, :], axis=1)  # totals from feature 0: (3,)
    g0, h0, c0 = sum0[0], sum0[1], sum0[2]

    tree0 = TreeArrays(
        num_leaves=jnp.asarray(1, jnp.int32),
        split_feature=jnp.zeros((L - 1,), jnp.int32),
        threshold_bin=jnp.zeros((L - 1,), jnp.int32),
        default_left=jnp.zeros((L - 1,), bool),
        split_gain=jnp.zeros((L - 1,), jnp.float32),
        left_child=jnp.zeros((L - 1,), jnp.int32),
        right_child=jnp.zeros((L - 1,), jnp.int32),
        internal_value=jnp.zeros((L - 1,), jnp.float32),
        internal_weight=jnp.zeros((L - 1,), jnp.float32),
        internal_count=jnp.zeros((L - 1,), jnp.float32),
        leaf_value=jnp.zeros((L,), jnp.float32),
        leaf_weight=jnp.zeros((L,), jnp.float32),
        leaf_count=jnp.zeros((L,), jnp.float32),
        leaf_sum_g=jnp.zeros((L,), jnp.float32),
        leaf_depth=jnp.zeros((L,), jnp.int32),
        is_cat=jnp.zeros((L - 1,), bool),
        cat_mask=jnp.zeros((L - 1, num_bins), bool),
    )

    use_used = interaction_sets is not None or track_path
    used0 = jnp.zeros((L, f), bool) if use_used else jnp.zeros((), bool)
    use_intermediate = (
        monotone_method == "intermediate" and monotone_constraints is not None
    )
    # CEGB lazy charges are row-global state; the distributed wrappers do
    # not thread them (rows are sharded), mirroring the strict grower
    use_lazy = (cegb_lazy_penalty is not None and cegb_lazy_used is not None
                and axis_name is None)
    leaf_out0 = leaf_output(g0, h0, params)
    cegb_used0 = jnp.zeros((f,), bool)
    cegb_pen0 = (
        jnp.where(cegb_used0, 0.0, cegb_feature_penalty)
        if cegb_feature_penalty is not None else None
    )

    if use_lazy:
        lazy_used0 = cegb_lazy_used
        lazy_counts0 = jnp.einsum(
            "n,nf->f", row_mask.astype(jnp.float32),
            (~lazy_used0).astype(jnp.float32))
    best0 = _set_best(
        _empty_best(L, num_bins), jnp.asarray(0),
        jax.tree.map(
            lambda a: a[0],
            _batched_best(
                hist0[None], jnp.asarray([g0]), jnp.asarray([h0]),
                jnp.asarray([c0]), num_bins_per_feature,
                missing_bin_per_feature, params, feature_mask,
                categorical_mask, monotone_constraints, interaction_sets,
                jnp.asarray([-jnp.inf], jnp.float32),
                jnp.asarray([jnp.inf], jnp.float32),
                used0[:1] if interaction_sets is not None else None,
                jnp.asarray([0], jnp.int32), rng_key,
                depth=jnp.asarray([0.0], jnp.float32),
                parent_out=jnp.asarray([leaf_out0]),
                cegb_pen=cegb_pen0,
                feature_contri=feature_contri,
                lazy_pen=cegb_lazy_penalty if use_lazy else None,
                lazy_counts=lazy_counts0[None] if use_lazy else None,
            ),
        ),
    )

    state = FastState(
        leaf_id=jnp.zeros((n,), jnp.int32),
        hist=jnp.zeros((L, 3, f, num_bins), jnp.float32).at[0].set(hist0),
        best=best0,
        leaf_sum_g=jnp.zeros((L,), jnp.float32).at[0].set(g0),
        leaf_sum_h=jnp.zeros((L,), jnp.float32).at[0].set(h0),
        leaf_count=jnp.zeros((L,), jnp.float32).at[0].set(c0),
        leaf_depth=jnp.zeros((L,), jnp.int32),
        leaf_parent=jnp.full((L,), -1, jnp.int32),
        leaf_side=jnp.zeros((L,), jnp.int32),
        num_leaves_cur=jnp.asarray(1, jnp.int32),
        leaf_out_lo=jnp.full((L,), -jnp.inf, jnp.float32),
        leaf_out_hi=jnp.full((L,), jnp.inf, jnp.float32),
        leaf_out=jnp.zeros((L,), jnp.float32).at[0].set(leaf_out0),
        cegb_used=cegb_used0,
        used_features=used0,
        fresh=jnp.zeros((L,), bool),
        small_slot=jnp.full((L,), -1, jnp.int32),
        slot_left=jnp.full((leaf_tile,), -1, jnp.int32),
        slot_right=jnp.full((leaf_tile,), -1, jnp.int32),
        slot_small_left=jnp.zeros((leaf_tile,), bool),
        progress=jnp.asarray(True),
        tree=tree0,
        anc=(jnp.zeros((L, L - 1), bool) if use_intermediate
             else jnp.zeros((), bool)),
        aside=(jnp.zeros((L, L - 1), bool) if use_intermediate
               else jnp.zeros((), bool)),
        lazy_used=(lazy_used0 if use_lazy else jnp.zeros((), bool)),
        lazy_counts=(jnp.zeros((L, f), jnp.float32).at[0].set(lazy_counts0)
                     if use_lazy else jnp.zeros((), bool)),
    )

    eps = KMIN_SCORE / 2

    def round_body(state: FastState, forced=None) -> FastState:
        # ---------- phase 1: accept splits for this round ----------
        if forced is None:
            gains = state.best.gain  # (L,) KMIN for unevaluated/exhausted
            can = gains > eps
            if max_depth > 0:
                can = can & (state.leaf_depth < max_depth)
            if use_intermediate:
                # Intermediate bounds make same-round splits INTERACT when
                # their leaves sit under a common monotone node: applying
                # one moves the opposite-subtree extremes the other was
                # searched against, and stacked constraints from different
                # ancestors can then clip a child into an EMPTY interval
                # (clip returns hi, breaching lo — a real monotonicity
                # violation, caught by the stress test).  Admit at most one
                # split per monotone-connected component and defer the
                # rest: a deferred leaf is re-searched next round under the
                # updated bounds (hist_and_eval re-evaluates every live
                # leaf), which reproduces the strict grower's sequential
                # semantics split-for-split.  A candidate conflicting with
                # ANY better-ranked candidate is deferred (slightly more
                # conservative than greedy-vs-admitted; one extra round at
                # worst).
                d_nodes = jnp.where(
                    state.tree.is_cat, 0,
                    monotone_constraints[state.tree.split_feature])
                mono_anc = (state.anc & (d_nodes != 0)[None, :]).astype(
                    jnp.float32)  # (L, L-1)
                conflict = (mono_anc @ mono_anc.T) > 0.5  # shared mono anc
                pre_rank = jnp.argsort(jnp.argsort(
                    jnp.where(can, -gains, jnp.inf)))
                better = pre_rank[None, :] < pre_rank[:, None]
                veto = jnp.any(conflict & better & can[None, :], axis=1) & can
                can = can & ~veto
            budget = L - state.num_leaves_cur  # how many new leaves fit
            # best-gain-first admission within budget, but at most leaf_tile
            # splits per round (one multi-hist pass).  The accepted set is a
            # PREFIX of the stable sort order (can-leaves sort first), so
            # the sort doubles as the rank->leaf map below — one argsort
            # fewer in the trace (round-7 warmup diet,
            # benchmarks/probe_trace_ops.py)
            srt = jnp.argsort(jnp.where(can, -gains, jnp.inf))
            order_rank = jnp.argsort(srt)
            accept = can & (order_rank < jnp.minimum(budget, leaf_tile))
            inv_rank = srt  # leaf at rank r; ranks >= k_acc are guarded by
            # accept[] at every use
            s = state.best  # vectorized split info (L,)
        else:
            # forced round (reference: ForceSplits): admit EXACTLY the
            # scheduled split so right-child numbering (split s -> leaf s+1)
            # matches the precomputed schedule; state.best is preserved for
            # the free-growth rounds that follow
            f_leaf, s_f, f_valid = forced
            accept = (jnp.arange(L, dtype=jnp.int32) == f_leaf) & f_valid
            order_rank = jnp.where(accept, 0, L)
            inv_rank = jnp.argsort(order_rank)  # forced leaf at rank 0
            s = jax.tree.map(lambda b, v: b.at[f_leaf].set(v), state.best, s_f)
        k_acc = jnp.sum(accept.astype(jnp.int32))

        # per accepted leaf: new node slot + right-child leaf id, ordered by rank
        acc_rank = jnp.where(accept, order_rank, L)  # (L,)
        node_of = state.num_leaves_cur - 1 + acc_rank  # node slot (valid where accept)
        right_of = state.num_leaves_cur + acc_rank  # right-child leaf id

        # ---------- row partition: all accepted splits at once ----------
        # Loop over the <= leaf_tile accepted slots with dynamic-slice COLUMN
        # reads — per-row take_along_axis gathers lower catastrophically on
        # TPU (measured ~30 ms/round), while 16 strided column slices +
        # elementwise selects cost ~0.2 ms.
        lid = state.leaf_id
        leaf_id = lid
        for r in range(leaf_tile):
            leaf_r = inv_rank[r]
            live = accept[leaf_r]  # rank r admitted?
            feat_r = s.feature[leaf_r]
            if bins_t is not None:
                fcol = jax.lax.dynamic_index_in_dim(
                    bins_t, feat_r, axis=0, keepdims=False
                ).astype(jnp.int32)
            else:
                fcol = jax.lax.dynamic_index_in_dim(
                    bins, feat_r, axis=1, keepdims=False
                ).astype(jnp.int32)
            miss_r = fcol == missing_bin_per_feature[feat_r]
            gl = jnp.where(miss_r, s.default_left[leaf_r], fcol <= s.threshold_bin[leaf_r])
            if categorical_mask is not None:
                gl = jnp.where(s.is_cat[leaf_r], s.cat_mask[leaf_r][fcol], gl)
            sel = live & (lid == leaf_r)
            leaf_id = jnp.where(sel & ~gl, right_of[leaf_r], leaf_id)

        # ---------- bookkeeping for accepted splits ----------
        idx = jnp.arange(L, dtype=jnp.int32)
        safe_node = jnp.clip(node_of, 0, L - 2)

        t = state.tree
        parent_out = state.leaf_out
        old_parent = state.leaf_parent
        old_side = state.leaf_side
        # re-point grandparent child slots from ~leaf to the new node
        # (out-of-range sentinel positions are dropped by the scatter)
        repoint_l = accept & (old_parent >= 0) & (old_side == 0)
        repoint_r = accept & (old_parent >= 0) & (old_side == 1)
        lc = t.left_child.at[jnp.where(repoint_l, old_parent, 2 * L)].set(
            safe_node, mode="drop")
        rc = t.right_child.at[jnp.where(repoint_r, old_parent, 2 * L)].set(
            safe_node, mode="drop")
        # new node's children: ~left_leaf, ~right_leaf
        node_pos = jnp.where(accept, node_of, 2 * L)
        lc = lc.at[node_pos].set(-idx - 1, mode="drop")
        rc = rc.at[node_pos].set(-right_of - 1, mode="drop")

        depth_child = state.leaf_depth + 1
        tree = t._replace(
            num_leaves=state.num_leaves_cur + k_acc,
            split_feature=t.split_feature.at[node_pos].set(s.feature, mode="drop"),
            threshold_bin=t.threshold_bin.at[node_pos].set(s.threshold_bin, mode="drop"),
            default_left=t.default_left.at[node_pos].set(s.default_left, mode="drop"),
            split_gain=t.split_gain.at[node_pos].set(s.gain, mode="drop"),
            left_child=lc,
            right_child=rc,
            internal_value=t.internal_value.at[node_pos].set(parent_out, mode="drop"),
            internal_weight=t.internal_weight.at[node_pos].set(state.leaf_sum_h, mode="drop"),
            internal_count=t.internal_count.at[node_pos].set(state.leaf_count, mode="drop"),
            is_cat=t.is_cat.at[node_pos].set(s.is_cat, mode="drop"),
            cat_mask=t.cat_mask.at[node_pos].set(s.cat_mask, mode="drop"),
        )

        # ---------- leaf aggregate updates (left keeps id, right gets new) ----------
        right_pos = jnp.where(accept, right_of, 2 * L)

        def upd(arr, left_val, right_val):
            arr = jnp.where(accept, left_val, arr)
            return arr.at[right_pos].set(right_val, mode="drop")

        leaf_sum_g = upd(state.leaf_sum_g, s.left_sum_g, s.right_sum_g)
        leaf_sum_h = upd(state.leaf_sum_h, s.left_sum_h, s.right_sum_h)
        leaf_count = upd(state.leaf_count, s.left_count, s.right_count)
        leaf_depth = jnp.where(accept, depth_child, state.leaf_depth)
        leaf_depth = leaf_depth.at[right_pos].set(depth_child, mode="drop")
        leaf_parent = jnp.where(accept, node_of, state.leaf_parent)
        leaf_parent = leaf_parent.at[right_pos].set(
            jnp.where(accept, node_of, 0), mode="drop")
        leaf_side = jnp.where(accept, 0, state.leaf_side)
        leaf_side = leaf_side.at[right_pos].set(1, mode="drop")

        # ---------- children outputs (path-smoothed) + monotone bounds ----------
        p_lo, p_hi = state.leaf_out_lo, state.leaf_out_hi
        out_l_c = leaf_output_smoothed(s.left_sum_g, s.left_sum_h, s.left_count,
                                       state.leaf_out, params)
        out_r_c = leaf_output_smoothed(s.right_sum_g, s.right_sum_h, s.right_count,
                                       state.leaf_out, params)
        if use_intermediate:
            # --- intermediate bounds under round-batched splits ---
            # Masks update vectorized: the left child keeps the parent's
            # leaf slot (ancestors + the new node, left side); the right
            # child's row adds the new node on the right side.
            node_oh = jax.nn.one_hot(
                jnp.where(accept, node_of, L), L - 1, dtype=bool)  # (L, L-1)
            anc_child = state.anc | node_oh
            anc = jnp.where(accept[:, None], anc_child, state.anc)
            anc = anc.at[right_pos].set(anc_child, mode="drop")
            aside = state.aside.at[right_pos].set(
                state.aside | node_oh, mode="drop")

            # Creation-time clipping: admitted splits are pairwise
            # NON-interacting (admission defers leaves sharing a monotone
            # ancestor, see phase 1), so each child's bounds are exactly
            # the parent's CURRENT stored bounds (state.leaf_out_lo/hi are
            # the end-of-last-round recompute over this same state).
            # Bounds are evaluated at the parent's slot: both children
            # share all ancestor constraints, and the new node's own
            # column contributes nothing at creation (its opposite side is
            # the not-yet-live sibling); sibling ordering is enforced by
            # the split search and preserved by clipping both children
            # into the same interval.
            lo_all, hi_all = state.leaf_out_lo, state.leaf_out_hi
            ol = jnp.clip(out_l_c, lo_all, hi_all)
            orr = jnp.clip(out_r_c, lo_all, hi_all)
            leaf_out = jnp.where(accept, ol, state.leaf_out)
            leaf_out = leaf_out.at[right_pos].set(orr, mode="drop")
            # rounds grower runs serial/data only — the constraint vector
            # is full-width here, so the per-node direction is a lookup
            node_mono = jnp.where(
                tree.is_cat, 0, monotone_constraints[tree.split_feature])
            leaf_out_lo, leaf_out_hi = _intermediate_bounds(
                anc, aside, node_mono, leaf_out,
                state.num_leaves_cur + k_acc, L,
            )
        else:
            if monotone_constraints is not None:
                mono_c = monotone_constraints[s.feature]
                out_l_c = jnp.clip(out_l_c, p_lo, p_hi)
                out_r_c = jnp.clip(out_r_c, p_lo, p_hi)
                mid = 0.5 * (out_l_c + out_r_c)
                l_hi = jnp.where(mono_c > 0, jnp.minimum(p_hi, mid), p_hi)
                r_lo = jnp.where(mono_c > 0, jnp.maximum(p_lo, mid), p_lo)
                l_lo = jnp.where(mono_c < 0, jnp.maximum(p_lo, mid), p_lo)
                r_hi = jnp.where(mono_c < 0, jnp.minimum(p_hi, mid), p_hi)
            else:
                l_lo, l_hi, r_lo, r_hi = p_lo, p_hi, p_lo, p_hi
            leaf_out_lo = jnp.where(accept, l_lo, state.leaf_out_lo)
            leaf_out_lo = leaf_out_lo.at[right_pos].set(r_lo, mode="drop")
            leaf_out_hi = jnp.where(accept, l_hi, state.leaf_out_hi)
            leaf_out_hi = leaf_out_hi.at[right_pos].set(r_hi, mode="drop")
            leaf_out = jnp.where(accept, out_l_c, state.leaf_out)
            leaf_out = leaf_out.at[right_pos].set(out_r_c, mode="drop")
            anc, aside = state.anc, state.aside
        cegb_used = state.cegb_used
        if cegb_feature_penalty is not None:
            cegb_used = cegb_used.at[
                jnp.where(accept, s.feature, 2 * f)
            ].set(True, mode="drop")

        if use_lazy:
            # charge every accepted leaf's in-bag rows for its split
            # feature, THEN count each child's uncharged rows (a child
            # split on the same feature is free) — the round-batched
            # mirror of the strict grower's per-split charge (reference:
            # CostEfficientGradientBoosting::UpdateUsedFeature)
            lazy_used = state.lazy_used
            for r in range(leaf_tile):
                leaf_r = inv_rank[r]
                live_r = accept[leaf_r]
                feat_r = s.feature[leaf_r]
                sel = live_r & (lid == leaf_r) & row_mask
                lazy_used = lazy_used.at[:, feat_r].set(
                    lazy_used[:, feat_r] | sel)
            # one pass counts all LEFT children (they keep the parent's
            # slot); the right child is the parent remainder with the
            # split feature zeroed on both sides
            oh_left = jnp.stack(
                [(accept[inv_rank[r]] & (leaf_id == inv_rank[r]) & row_mask)
                 for r in range(leaf_tile)], axis=1).astype(jnp.float32)
            counts_left = jnp.einsum(
                "nt,nf->tf", oh_left, (~lazy_used).astype(jnp.float32))
            lazy_counts = state.lazy_counts
            for r in range(leaf_tile):
                leaf_r = inv_rank[r]
                live_r = accept[leaf_r]
                feat_r = s.feature[leaf_r]
                parent_cnt = lazy_counts[leaf_r].at[feat_r].set(0.0)
                cl = counts_left[r].at[feat_r].set(0.0)
                cr = jnp.maximum(parent_cnt - cl, 0.0)
                rp = jnp.clip(right_of[leaf_r], 0, L - 1)
                lazy_counts = jnp.where(
                    live_r, lazy_counts.at[leaf_r].set(cl).at[rp].set(cr),
                    lazy_counts)
        else:
            lazy_used, lazy_counts = state.lazy_used, state.lazy_counts

        if use_used:
            used_child = jnp.where(
                accept[:, None],
                state.used_features | jax.nn.one_hot(s.feature, f, dtype=bool),
                state.used_features,
            )
            used_features = used_child.at[right_pos].set(used_child, mode="drop")
        else:
            used_features = state.used_features

        # ---------- fresh/small bookkeeping ----------
        left_smaller = s.left_count <= s.right_count
        fresh = jnp.zeros((L,), bool)
        fresh = jnp.where(accept, True, fresh)
        fresh = fresh.at[right_pos].set(True, mode="drop")
        small_leaf = jnp.where(left_smaller, idx, right_of)  # per accepted split
        slot = jnp.where(accept, acc_rank, -1)  # pass slot = admission rank
        small_slot = jnp.full((L,), -1, jnp.int32)
        small_pos = jnp.where(accept, small_leaf, 2 * L)
        small_slot = small_slot.at[small_pos].set(slot, mode="drop")
        # per-slot child maps: the parent's hist stays in the LEFT child's
        # state slot (left keeps the parent's leaf id), so the pass phase
        # gathers parents and subtracts on compact (tile,...) arrays — no
        # full-state parent snapshot (it measured 17 ms/round at Epsilon
        # shape; benchmarks/probe_r5_fixed.py)
        pos_r = jnp.where(accept, acc_rank, leaf_tile)
        slot_left = jnp.full((leaf_tile,), -1, jnp.int32).at[pos_r].set(
            idx, mode="drop")
        slot_right = jnp.full((leaf_tile,), -1, jnp.int32).at[pos_r].set(
            right_of, mode="drop")
        slot_small_left = jnp.zeros((leaf_tile,), bool).at[pos_r].set(
            left_smaller, mode="drop")
        hist = state.hist

        # invalidate best for split leaves (children evaluated next round)
        best = state.best
        kmin = jnp.full((L,), KMIN_SCORE, jnp.float32)
        best = best._replace(gain=jnp.where(fresh, kmin, best.gain))

        return FastState(
            leaf_id=leaf_id,
            hist=hist,
            best=best,
            leaf_sum_g=leaf_sum_g,
            leaf_sum_h=leaf_sum_h,
            leaf_count=leaf_count,
            leaf_depth=leaf_depth,
            leaf_parent=leaf_parent,
            leaf_side=leaf_side,
            num_leaves_cur=state.num_leaves_cur + k_acc,
            leaf_out_lo=leaf_out_lo,
            leaf_out_hi=leaf_out_hi,
            leaf_out=leaf_out,
            cegb_used=cegb_used,
            used_features=used_features,
            fresh=fresh,
            small_slot=small_slot,
            slot_left=slot_left,
            slot_right=slot_right,
            slot_small_left=slot_small_left,
            progress=k_acc > 0,
            tree=tree,
            anc=anc,
            aside=aside,
            lazy_used=lazy_used,
            lazy_counts=lazy_counts,
        )

    def hist_and_eval(state: FastState) -> FastState:
        # ---------- phase 2: one pass for all small children ----------
        # slot per row (small_slot[leaf_id]) via a static slot loop — small
        # table gathers at (N,) lower poorly on TPU (see partition above)
        lid = state.leaf_id
        leaf_slot = jnp.full((n,), -1, jnp.int32)
        for r in range(leaf_tile):
            has_r = state.small_slot == r  # (L,)
            leaf_r = jnp.argmax(has_r).astype(jnp.int32)
            exists = jnp.any(has_r)
            leaf_slot = jnp.where(exists & (lid == leaf_r), r, leaf_slot)
        fresh_hists = multi_hist(leaf_slot, leaf_tile)  # (leaf_tile, 3, F, B)
        idx = jnp.arange(L, dtype=jnp.int32)
        # COMPACT sibling recovery (round 5): parent hists live in the left
        # children's slots; gather the <= tile parents, subtract, and
        # scatter both children once — O(tile) state traffic instead of the
        # full-(L,...) scatter/subtract/where chain (measured 57 ms/round
        # at Epsilon shape; benchmarks/probe_r5_fixed.py)
        active = state.slot_left >= 0  # (tile,)
        sl = jnp.clip(state.slot_left, 0, L - 1)
        sr = jnp.clip(state.slot_right, 0, L - 1)
        parent_hists = state.hist[sl]  # (tile, 3, F, B)
        big_hists = parent_hists - fresh_hists
        sml = state.slot_small_left[:, None, None, None]
        left_hists = jnp.where(sml, fresh_hists, big_hists)
        right_hists = jnp.where(sml, big_hists, fresh_hists)
        lpos = jnp.where(active, sl, 2 * L)
        rpos = jnp.where(active, sr, 2 * L)
        hist = state.hist.at[lpos].set(left_hists, mode="drop").at[rpos].set(
            right_hists, mode="drop")

        # ---------- phase 3: evaluate fresh leaves (one vmapped search) ----------
        node_ids = jnp.clip(state.leaf_parent, 0, None) * 2 + state.leaf_side + 1
        cegb_pen = (
            jnp.where(state.cegb_used, 0.0, cegb_feature_penalty)
            if cegb_feature_penalty is not None else None
        )
        if use_intermediate:
            # bounds of EVERY leaf may have moved this round (their opposite
            # subtrees changed), so cached best splits are stale — re-search
            # all live leaves (reference: IntermediateLeafConstraints'
            # leaves_to_update set; recompute-all is the vectorized exact
            # equivalent, same trade as the strict grower makes)
            bb = _batched_best(
                hist, state.leaf_sum_g, state.leaf_sum_h, state.leaf_count,
                num_bins_per_feature, missing_bin_per_feature, params,
                feature_mask, categorical_mask, monotone_constraints,
                interaction_sets, state.leaf_out_lo, state.leaf_out_hi,
                state.used_features if interaction_sets is not None else None,
                node_ids, rng_key,
                depth=state.leaf_depth, parent_out=state.leaf_out,
                cegb_pen=cegb_pen,
                feature_contri=feature_contri,
                lazy_pen=cegb_lazy_penalty if use_lazy else None,
                lazy_counts=state.lazy_counts if use_lazy else None,
            )
            live = idx < state.num_leaves_cur
            best = bb._replace(gain=jnp.where(live, bb.gain, KMIN_SCORE))
            return state._replace(
                hist=hist, best=best,
                fresh=jnp.zeros((L,), bool),
                small_slot=jnp.full((L,), -1, jnp.int32),
                slot_left=jnp.full((leaf_tile,), -1, jnp.int32),
                slot_right=jnp.full((leaf_tile,), -1, jnp.int32),
                slot_small_left=jnp.zeros((leaf_tile,), bool))
        # only the fresh children need evaluation, and their hists are
        # ALREADY compact (left_hists/right_hists above): feed the search
        # directly instead of re-gathering (2*tile, 3, F, B) from the state
        # (that gather measured 18 ms/round at Epsilon shape)
        cand = jnp.concatenate([sl, sr])  # (2*tile,) candidate leaf ids
        cand_ok = jnp.concatenate([active, active])
        cand_hists = jnp.concatenate([left_hists, right_hists], axis=0)
        ci = jnp.where(cand_ok, cand, 0)
        bb = _batched_best(
            cand_hists, state.leaf_sum_g[ci], state.leaf_sum_h[ci],
            state.leaf_count[ci],
            num_bins_per_feature, missing_bin_per_feature, params,
            feature_mask, categorical_mask, monotone_constraints,
            interaction_sets, state.leaf_out_lo[ci], state.leaf_out_hi[ci],
            state.used_features[ci] if interaction_sets is not None else None,
            node_ids[ci], rng_key,
            depth=state.leaf_depth[ci], parent_out=state.leaf_out[ci],
            cegb_pen=cegb_pen,
            feature_contri=feature_contri,
            lazy_pen=cegb_lazy_penalty if use_lazy else None,
            lazy_counts=state.lazy_counts[ci] if use_lazy else None,
        )
        scatter_pos = jnp.where(cand_ok, cand, 2 * L)  # drop inactive slots

        def merge(old, new):
            return old.at[scatter_pos].set(new, mode="drop")

        best = BestSplit(*[merge(o, nw) for o, nw in zip(state.best, bb)])
        return state._replace(
            hist=hist, best=best,
            fresh=jnp.zeros((L,), bool),
            small_slot=jnp.full((L,), -1, jnp.int32),
            slot_left=jnp.full((leaf_tile,), -1, jnp.int32),
            slot_right=jnp.full((leaf_tile,), -1, jnp.int32),
            slot_small_left=jnp.zeros((leaf_tile,), bool))

    def cond(state: FastState):
        more_leaves = state.num_leaves_cur < L
        any_gain = jnp.max(state.best.gain) > eps
        return state.progress & more_leaves & any_gain

    def body(state: FastState):
        state = round_body(state)
        return jax.lax.cond(
            state.progress, hist_and_eval, lambda st: st, state
        )

    if n_forced > 0:
        # forced prefix (reference: SerialTreeLearner::ForceSplits): one
        # single-split round per schedule entry, BEFORE gain-driven growth.
        # The candidate is evaluated through the standard gain plane masked
        # to the scheduled (feature, bin) cell, so min_data/min_hess/monotone
        # gates apply; the first invalid entry disables the rest (the
        # schedule's leaf ids assume every prior entry applied).
        def forced_candidate(state: FastState, i: int):
            fl = jnp.clip(forced_leaf[i], 0, L - 1)
            s_f = forced_split_candidate(
                state.hist[fl], state.leaf_sum_g[fl], state.leaf_sum_h[fl],
                state.leaf_count[fl], num_bins_per_feature,
                missing_bin_per_feature, params,
                forced_feature[i], forced_bin[i],
                categorical_mask=categorical_mask,
                monotone_constraints=monotone_constraints,
                out_lo=state.leaf_out_lo[fl], out_hi=state.leaf_out_hi[fl],
                depth=state.leaf_depth[fl].astype(jnp.float32),
                parent_output=state.leaf_out[fl],
                feature_contri=feature_contri,
            )
            valid = (
                (forced_leaf[i] < state.num_leaves_cur)
                & (state.num_leaves_cur < L)
                & (s_f.gain > KMIN_SCORE / 2)
            )
            if max_depth > 0:
                valid = valid & (state.leaf_depth[fl] < max_depth)
            return fl, s_f, valid

        forced_ok = jnp.asarray(True)
        for i in range(n_forced):
            fl, s_f, valid = forced_candidate(state, i)
            valid = valid & forced_ok
            forced_ok = valid
            state = round_body(state, forced=(fl, s_f, valid))
            state = jax.lax.cond(state.progress, hist_and_eval,
                                 lambda st: st, state)
        # a rejected forced entry leaves progress=False; free growth still runs
        state = state._replace(progress=jnp.asarray(True))

    state = jax.lax.while_loop(cond, body, state)

    if quant_renew and quantize_bins and not use_intermediate:
        # recompute leaf outputs from the TRUE f32 gradients (reference:
        # GBDT::Train -> RenewIntGradTreeOutput after quantized growth)
        mrow = row_mask.astype(jnp.float32)
        Gt = psum(jnp.zeros((L,), jnp.float32).at[state.leaf_id].add(grad_true * mrow))
        Ht = psum(jnp.zeros((L,), jnp.float32).at[state.leaf_id].add(hess_true * mrow))
        leaf_value = leaf_output(Gt, Ht, params)
        if monotone_constraints is not None:
            leaf_value = jnp.clip(leaf_value, state.leaf_out_lo, state.leaf_out_hi)
    elif params.path_smooth > 0 or use_intermediate:
        # smoothed / monotone-clipped AT CREATION.  Under intermediate
        # bounds this is required for correctness: bounds keep evolving
        # after a leaf is created, and re-clipping recomputed outputs to
        # the FINAL bounds can cross a monotone split (see treegrow.py) —
        # which is also why quantized renewal is skipped above when
        # intermediate is active.
        leaf_value = state.leaf_out
    else:
        leaf_value = leaf_output(state.leaf_sum_g, state.leaf_sum_h, params)
        if monotone_constraints is not None:
            leaf_value = jnp.clip(leaf_value, state.leaf_out_lo, state.leaf_out_hi)
    active = jnp.arange(L, dtype=jnp.int32) < state.num_leaves_cur
    tree = state.tree._replace(
        num_leaves=state.num_leaves_cur,
        leaf_value=jnp.where(active, leaf_value, 0.0),
        leaf_weight=jnp.where(active, state.leaf_sum_h, 0.0),
        leaf_count=jnp.where(active, state.leaf_count, 0.0),
        leaf_sum_g=jnp.where(active, state.leaf_sum_g, 0.0),
        leaf_depth=state.leaf_depth,
        path_features=(state.used_features if track_path else None),
    )
    if use_lazy:
        # hand the cross-tree charge state back (reference: the
        # feature_used_in_data bitset persists across trees)
        return tree, state.leaf_id, state.lazy_used
    return tree, state.leaf_id


def grow_tree_fast(*args, use_pallas: bool = True, **kwargs):
    """Public entry: :func:`_grow_fast_impl` behind the graceful
    kernel-degradation net (utils/degrade.py, mirrored from
    ops/treegrow_windowed.py::grow_tree_windowed).  ``use_pallas`` folds
    in the degradation registry before becoming a jit static; a Pallas
    failure surfacing at trace or backend-COMPILE time is caught once,
    logged, and the tree regrown on the XLA histogram path.

    Honest scope: unlike the windowed grower (whose driver resolves
    device reads inside the impl, so execute-time kernel failures surface
    here too), this impl returns un-materialized device arrays — an
    ASYNC execute-time kernel failure surfaces at the caller's next
    blocking pull, outside this net.  Compile-time rejection is the
    dominant real-world Mosaic failure class; the env escape hatches
    remain for the rest."""
    if not (use_pallas and _degrade.available(_degrade.HIST)):
        return _grow_fast_impl(*args, use_pallas=False, **kwargs)
    return _degrade.run_with_fallback(
        _degrade.HIST,
        lambda: _grow_fast_impl(*args, use_pallas=True, **kwargs),
        lambda: _grow_fast_impl(*args, use_pallas=False, **kwargs))
