"""Training entry points: train() and cv().

Reference: python-package/lightgbm/engine.py — train(), cv(), CVBooster,
callback ordering by `.order` / `.before_iteration`, EarlyStopException flow.
"""

from __future__ import annotations

import copy
import os
import time
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, CorruptModelError, Dataset, LightGBMError
from .callback import CallbackEnv, EarlyStopException
from .config import Config, choose_param_value
from .obs import metrics as _obs
from .obs import server as _obs_server
from .obs import trace as _trace
from .utils import checkpoint as _checkpoint
from .utils import faults as _faults
from .utils.log import log_debug, log_info, log_warning, set_verbosity


def _load_init_booster(init_model) -> Booster:
    """Booster from init_model; a snapshot that fails integrity
    verification falls back to the newest VALID snapshot in its family
    instead of dying on (or worse, silently half-loading) a torn file
    (docs/ROBUSTNESS.md)."""
    if isinstance(init_model, Booster):
        return init_model
    try:
        return Booster(model_file=init_model)
    except CorruptModelError as corrupt:
        # scan strictly OLDER siblings: a stale NEWER snapshot (from a
        # previous, longer run sharing the prefix) would resume with the
        # wrong trees — older-than-requested is the only safe direction
        below = _checkpoint.snapshot_iteration(init_model)
        fb = _checkpoint.latest_valid_snapshot(init_model, below_iter=below)
        if fb is not None:
            it, snap = fb
            _obs.counter("checkpoint_fallbacks_total").inc()
            _obs.event("checkpoint_fallback", requested=str(init_model),
                       used=snap, iteration=it)
            log_warning(
                f"init_model {init_model} failed integrity verification; "
                f"falling back to the newest valid older snapshot {snap} "
                f"(iteration {it})")
            return Booster(model_file=snap)
        # last resort: a PRE-TRAILER-ERA snapshot (no trailer at all but
        # otherwise intact) — load unverified rather than abandoning the
        # whole checkpoint family.  A truncated file usually loses its
        # trailer too and looks identical, and the parser tolerates
        # missing tail blocks — so demand the format's own structural
        # completeness markers ("end of trees" + every tree block the
        # tree_sizes header promises) before the benefit of the doubt.
        text, ok = _checkpoint.read_and_verify(init_model)
        if ok is None and "\nend of trees" in text:
            import re as _re

            m = _re.search(r"^tree_sizes=(.*)$", text, _re.M)
            expected_trees = len(m.group(1).split()) if m else -1
            try:
                booster = Booster(model_str=text)
            except Exception:  # noqa: BLE001 — torn after all
                raise corrupt from None
            if booster.num_trees() != expected_trees:
                raise corrupt from None
            log_warning(
                f"init_model {init_model} is a snapshot with no integrity "
                "trailer (pre-trailer format); no verified fallback exists "
                "— loading it UNVERIFIED as a last resort. Re-snapshot "
                "after this run to upgrade the family "
                "(docs/ROBUSTNESS.md)")
            return booster
        raise


def train(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    valid_sets: Optional[List[Dataset]] = None,
    valid_names: Optional[List[str]] = None,
    feval: Optional[Callable] = None,
    init_model: Optional[Union[str, Booster]] = None,
    keep_training_booster: bool = False,
    callbacks: Optional[List[Callable]] = None,
    resume: Optional[str] = None,
) -> Booster:
    """reference: engine.py train().

    ``resume="auto"`` (ours; also reachable as the ``resume=auto`` config/CLI
    param): pick up the newest VALID snapshot in ``output_model``'s family
    (utils/checkpoint.py latest_valid_snapshot) without naming a file, and
    train only the REMAINING rounds toward ``num_boost_round`` — crash
    recovery becomes re-running the original command (docs/ROBUSTNESS.md;
    the round-8 fallback handled a torn *named* snapshot, this closes the
    queued round-9 follow-up of not having to name one at all)."""
    params = dict(params or {})
    params = choose_param_value("num_iterations", params, None)
    if params.get("num_iterations") is not None:
        num_boost_round = int(params["num_iterations"])
    params["num_iterations"] = num_boost_round
    params = choose_param_value("early_stopping_round", params, None)
    early_stopping_round = params.get("early_stopping_round")
    cfg_probe = Config.from_dict(params)
    set_verbosity(cfg_probe.verbosity)
    # live introspection opt-in (docs/OBSERVABILITY.md): metrics_port= (or
    # LGBMTPU_METRICS_PORT) starts the process-wide /metrics + /healthz
    # endpoint before the first round, so the whole run is scrapeable.
    # Port conflicts fall back to an ephemeral port; nothing here may
    # cost the caller a model.
    telemetry_on = (bool(cfg_probe.telemetry) if cfg_probe.is_set("telemetry")
                    else _obs.DEFAULT_ENABLED)
    if telemetry_on:
        try:
            _obs_server.maybe_start(
                cfg_probe.metrics_port if cfg_probe.is_set("metrics_port")
                else None)
        except OSError as e:
            # an unbindable endpoint (fd exhaustion, no loopback in a
            # sandbox) must never cost the caller a model — the fallback
            # inside start() covers busy ports; this covers everything else
            log_warning(f"metrics endpoint could not start: {e}")

    resume = resume if resume is not None else (cfg_probe.resume or None)
    if resume is not None and resume != "auto":
        # resume=<fleet manifest> (docs/ROBUSTNESS.md "Elastic fleet
        # recovery"): the launcher's relaunch path hands every rank the
        # newest FLEET-VALID manifest; a torn or unconfirmed one is
        # refused outright — resuming into inconsistent fleet state would
        # silently fork the ranks' models.
        if init_model is not None:
            # precedence decided FIRST: a manifest that will be ignored
            # must not be able to abort the run on its own staleness
            log_warning("resume=<manifest> ignored: an explicit init_model "
                        "was given and takes precedence")
        else:
            if not os.path.exists(resume):
                raise LightGBMError(
                    f"resume={resume!r} is not supported: pass 'auto', a "
                    "fleet manifest path (lgbmtpu-fleet-ckpt-v1), or "
                    "init_model=<snapshot> for a specific file")
            # slice-granular recovery (docs/ROBUSTNESS.md): the launcher
            # respawning ONE lost slice names its dead ranks here, so a
            # round every SURVIVING rank confirmed is resumable even
            # though the lost slice's own acks are missing
            excl = tuple(
                int(r) for r in os.environ.get(
                    "LGBMTPU_RESUME_EXCLUDE_RANKS", "").split(",") if r)
            manifest = _checkpoint.fleet_manifest_valid(
                resume, exclude_ranks=excl)
            if manifest is None:
                raise LightGBMError(
                    f"resume manifest {resume} is not fleet-valid (torn, "
                    "unconfirmed by some rank, or its snapshot fails "
                    "verification) — refusing to resume into inconsistent "
                    "fleet state (docs/ROBUSTNESS.md)")
            rank = os.environ.get("LGBM_TPU_WORKER_ID",
                                  os.environ.get("LIGHTGBM_TPU_RANK", "0"))
            shard_fp = os.environ.get("LGBMTPU_SHARD_FINGERPRINT")
            want_fp = (manifest.get("shards") or {}).get(rank)
            if shard_fp and want_fp and shard_fp != want_fp:
                raise LightGBMError(
                    f"rank {rank}'s data shard fingerprint {shard_fp[:12]}… "
                    f"does not match the manifest's {want_fp[:12]}… — the "
                    "shard changed since the checkpoint; resuming would "
                    "train round k+1 on different data than rounds 1..k")
            it = int(manifest["round"])
            if it > num_boost_round:
                # overshoot guard (the resume='auto' branch bounds its
                # scan with below_iter for the same reason): silently
                # returning a model with MORE iterations than requested
                # is the stale-newer hazard, not a resume
                raise LightGBMError(
                    f"resume manifest {resume} is at round {it}, beyond "
                    f"the requested num_iterations={num_boost_round} — "
                    "raise num_iterations or resume from an older "
                    "manifest")
            init_model = manifest["snapshot"]
            num_boost_round = max(num_boost_round - it, 0)
            _obs.counter("fleet_resumes_total").inc()
            _obs.gauge("fleet_resumed_round").set(it)
            _obs.event("fleet_resume", round=it, manifest=os.fspath(resume),
                       snapshot=manifest["snapshot"])
            # the resume leg joins the trace vocabulary (ISSUE-20): a
            # rollover/relaunch reconstructs from the merged fleet trace
            # next to the serve/request spans it interleaved with
            _trace.record_span("checkpoint.resume", 0.0, round=it,
                               manifest=os.fspath(resume),
                               outcome="fleet_manifest")
            log_info(
                f"resume: fleet manifest {resume} (round {it}) — training "
                f"{num_boost_round} remaining round(s) from its snapshot")
    elif resume is not None:
        if init_model is not None:
            log_warning("resume='auto' ignored: an explicit init_model was "
                        "given and takes precedence")
        else:
            # restrict to snapshots AT OR BELOW the target iteration: a
            # newer snapshot from a previous, longer run sharing the prefix
            # would overshoot the requested model (the same stale-newer
            # hazard the torn-snapshot fallback guards against)
            fb = _checkpoint.latest_valid_snapshot(
                cfg_probe.output_model, below_iter=num_boost_round + 1)
            if fb is not None:
                it, snap = fb
                init_model = snap
                num_boost_round = max(num_boost_round - it, 0)
                _trace.record_span("checkpoint.resume", 0.0, round=it,
                                   snapshot=os.fspath(snap),
                                   outcome="auto_snapshot")
                log_info(
                    f"resume=auto: resuming from {snap} (iteration {it}); "
                    f"training {num_boost_round} remaining round(s)")
            else:
                log_info("resume=auto: no valid snapshot found for "
                         f"{cfg_probe.output_model}; starting fresh")

    fobj = None
    if callable(params.get("objective")):
        fobj = params["objective"]
        params["objective"] = "none"

    booster = Booster(params=params, train_set=train_set)
    if init_model is not None:
        init_booster = _load_init_booster(init_model)
        # continued training (reference: GBDT continued training via
        # input_model): seed with the source model's trees, then replay
        # scores so the fresh booster's own boost_from_average must not
        # contribute twice.
        import numpy as _np
        from .models.gbdt import GBDT as _GBDT

        gbdt = booster._gbdt
        src = init_booster._gbdt
        if src.average_output:
            # RF keeps the folded round-trip: averaged output folds the
            # init score into EVERY tree, so the separated-init replay
            # below would double-count it
            seeded = _GBDT.load_model_from_string(
                init_booster.model_to_string())
            gbdt.models = seeded.models
            gbdt.iter_ = seeded.iter_
            gbdt.init_scores = [0.0] * gbdt.num_tree_per_iteration
        else:
            # seed with the source's EXACT state: pure-delta trees plus
            # the init score kept separate (raw-delta snapshots and
            # in-memory boosters carry it; legacy folded model files load
            # with init_scores == 0 and folded trees, which reduces to the
            # old behavior).  Rebuilding the score base as fl32(init) and
            # replaying fl32(delta) per tree reproduces the live run's
            # accumulation order, so crash-resume from a raw-delta
            # snapshot is BITWISE-identical to uninterrupted training
            # (docs/ROBUSTNESS.md "Elastic fleet recovery").
            gbdt.models = copy.deepcopy(src.models)
            gbdt.iter_ = (len(src.models)
                          // max(gbdt.num_tree_per_iteration, 1))
            gbdt.init_scores = list(src.init_scores)
        base = _np.zeros(gbdt._score.shape, dtype=_np.float32)
        if any(s != 0.0 for s in gbdt.init_scores):
            if gbdt.num_tree_per_iteration == 1:
                base += _np.float32(gbdt.init_scores[0])
            else:
                base += _np.asarray(gbdt.init_scores,
                                    dtype=_np.float32)[None, :]
        if train_set.init_score is not None:
            base += _np.asarray(train_set.init_score, _np.float32).reshape(base.shape)
        import jax.numpy as _jnp

        gbdt._score = _jnp.asarray(base)
        _replay_scores(gbdt)

    valid_sets = valid_sets or []
    valid_names = valid_names or []
    for i, vs in enumerate(valid_sets):
        if vs is train_set:
            name = valid_names[i] if i < len(valid_names) else "training"
            booster._gbdt.train_name = name
            continue
        name = valid_names[i] if i < len(valid_names) else f"valid_{i}"
        booster.add_valid(vs, name)

    callbacks = list(callbacks or [])
    if early_stopping_round is not None and int(early_stopping_round) > 0:
        from .callback import early_stopping

        callbacks.append(
            early_stopping(
                int(early_stopping_round),
                first_metric_only=bool(params.get("first_metric_only", False)),
                verbose=cfg_probe.verbosity >= 1,
                min_delta=float(params.get("early_stopping_min_delta", 0.0)),
            )
        )
    for cb in callbacks:
        if not hasattr(cb, "order"):
            cb.order = 0  # type: ignore[attr-defined]
    callbacks_before = [cb for cb in callbacks if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: cb.order)
    callbacks_after.sort(key=lambda cb: cb.order)

    train_in_valids = any(vs is train_set for vs in (valid_sets or []))

    snapshot_freq = int(cfg_probe.snapshot_freq)
    # snapshot names carry GLOBAL iteration numbers: a resumed run (this
    # call's round i continues init_model's iterations) must not overwrite
    # snapshot_iter_2 with a 6-tree model — the fallback scan and the
    # "train (total - k) more rounds" resume recipe both trust the name
    snapshot_base = booster.current_iteration()

    # request-scoped tracing knobs apply process-wide here, like the
    # registry's enablement — admission points (serve submit, /predict)
    # read them when minting per-request contexts
    _trace.configure_request_tracing(cfg_probe.request_tracing,
                                     cfg_probe.trace_sample)
    trace_out = _trace_path(cfg_probe)
    if _obs.enabled() and trace_out:
        # ring-overflow spill sink rides the trace_file= opt-in
        # (obs/trace.py): a long (out-of-core) run can no longer lose
        # spans silently — evictions append to the sidecar JSONL and
        # count trace_spans_spilled_total.  Best-effort, like the final
        # write_trace: an unwritable sidecar must not cost the run.
        try:
            _trace.enable_spill(trace_out + ".spill.jsonl")
        except OSError as e:
            log_warning("could not arm the trace spill sink next to "
                        f"{trace_out}: {e}")

    # the run-level span is HOST-CAUSAL wall clock (docs/OBSERVABILITY.md
    # "Span tracing"): per-round device-inclusive spans are the windowed
    # grower's, anchored at its accounted async-info resolves
    train_span = _trace.span("train", num_boost_round=num_boost_round)
    train_span.__enter__()
    # arm the heartbeat: heartbeat_done=0 marks this process as actively
    # training, so the launcher's hang watchdog tracks staleness; the
    # finally below retires it — otherwise the post-training tail (model
    # save, final eval, fleet ack) would read as a stalled heartbeat and
    # a slow endgame could be killed as a false hang
    _obs.gauge("heartbeat_done").set(0.0)
    try:
        for i in range(num_boost_round):
            # heartbeat (docs/ROBUSTNESS.md "Elastic fleet recovery"): a
            # monotonic host-clock gauge bumped by the MAIN thread each
            # round and flushed by the existing periodic metrics snapshot
            # — the launcher's hang watchdog declares a rank hung when
            # the VALUE stops changing, so a rank wedged inside a
            # collective is caught even though its snapshot-writer daemon
            # thread keeps the file fresh.  One host gauge write: zero
            # device dispatches, zero new threads.
            _obs.gauge("heartbeat_ts").set(time.monotonic())
            # fault-injection sites: preemption (hard exit) or a wedged
            # collective (sleep forever) at the start of 1-based iteration
            # i+1 (utils/faults.py; recovery = manifest/snapshot resume)
            _faults.maybe_crash("host_crash", i + 1)
            _faults.maybe_hang("worker_hang", i + 1)
            for cb in callbacks_before:
                cb(CallbackEnv(booster, params, i, 0, num_boost_round, []))
            finished = booster.update(fobj=fobj)
            evaluation_result_list = []
            if train_in_valids or booster._gbdt.cfg.is_provide_training_metric:
                evaluation_result_list.extend(booster.eval_train(feval))
            evaluation_result_list.extend(booster.eval_valid(feval))
            for cb in callbacks_after:
                cb(CallbackEnv(booster, params, i, 0, num_boost_round, evaluation_result_list))
            global_iter = snapshot_base + i + 1
            if snapshot_freq > 0 and global_iter % snapshot_freq == 0:
                # periodic failure-recovery snapshot (reference: CLI
                # snapshot_freq / save_period — GBDT::Train saves
                # model_output_path.snapshot_iter_<n> every freq iterations)
                snap = f"{cfg_probe.output_model}.snapshot_iter_{global_iter}"
                # atomic + integrity-trailed (utils/checkpoint.py): a crash
                # mid-write can no longer leave a torn snapshot that a
                # restart would load.  raw_deltas: snapshots carry pure-delta
                # trees + an init_scores header so resume is bitwise
                with _trace.span("checkpoint.snapshot",
                                 iteration=global_iter, path=snap):
                    _checkpoint.save_snapshot(
                        snap, booster.model_to_string(raw_deltas=True),
                        global_iter)
                log_info(f"Saved snapshot to {snap}")
                if int(cfg_probe.snapshot_keep) > 0:
                    # bounded retention (snapshot_keep=): prune the oldest
                    # snapshots AFTER the new one landed; the newest
                    # verifying snapshot is never pruned
                    _checkpoint.prune_snapshots(cfg_probe.output_model,
                                                int(cfg_probe.snapshot_keep))
            if finished:
                log_info("Stopped training because there are no more leaves that meet the split requirements")
                break
    except EarlyStopException as e:
        booster.best_iteration = e.best_iteration + 1
        for item in e.best_score:
            booster.best_score.setdefault(item[0], {})[item[1]] = item[2]
        train_span.set(early_stopped=True)
    finally:
        # retire the heartbeat BEFORE the endgame (save/eval/ack tail can
        # legitimately exceed the hang timeout); the periodic snapshot
        # flushes it within one period
        _obs.gauge("heartbeat_done").set(1.0)
        train_span.set(trained_iterations=booster.current_iteration())
        train_span.__exit__(None, None, None)
        # report (and the spill-sink disarm inside it) must run on EVERY
        # exit path — a fault/non-finite abort that skipped it would leave
        # the sink armed process-wide, appending later unrelated work's
        # evictions to this run's sidecar
        _finish_run_report(cfg_probe)
    if booster.best_iteration <= 0:
        booster.best_iteration = booster.current_iteration()
    return booster


def serve(model=None, params: Optional[Dict[str, Any]] = None, *,
          models=None, start: bool = True):
    """Serving entry point (README "Serving"): build — and by default
    START — an in-process :class:`~lightgbm_tpu.serve.ServingRuntime`
    over one or more trained models, with the live ``/metrics`` +
    ``/healthz`` endpoint brought up exactly as ``train`` does.

    ``model`` is a :class:`Booster` or a model-file path (single-model,
    served as ``"default"``); ``models`` is a ``{name: Booster|path}``
    table for multi-tenant serving.  ``params`` carries the serve knobs
    (``serve_max_wait_ms``, ``serve_max_queue``, ``serve_slo_p99_ms``,
    ``serve_tenant_quota``) plus ``metrics_port=``/``telemetry=`` — the
    same Config names as everywhere else (docs/Parameters.md).  Setting
    ANY fleet knob (``serve_replicas``, ``serve_deadline_ms``,
    ``serve_hedge_ms``, ``serve_retry_budget``, ``serve_replica_trip``,
    ``serve_replica_cooldown_ms``, ``serve_hang_timeout_ms``,
    ``serve_restart_backoff_ms``, ``serve_max_restarts``) builds a
    :class:`~lightgbm_tpu.serve.ServingFleet` instead — health-routed
    replicas, deadlines, exactly-once retry and the restart watchdog.

    >>> rt = lgb.serve(booster, {"serve_max_wait_ms": 2})
    >>> y = rt.predict(X); rt.stop()
    >>> fl = lgb.serve(booster, {"serve_replicas": 2,
    ...                          "serve_deadline_ms": 50})
    """
    from .serve.fleet import ServingFleet
    from .serve.runtime import ServingRuntime

    cfg = Config.from_dict(dict(params or {}))
    set_verbosity(cfg.verbosity)
    telemetry_on = (bool(cfg.telemetry) if cfg.is_set("telemetry")
                    else _obs.DEFAULT_ENABLED)
    _obs.set_enabled(telemetry_on)
    if telemetry_on:
        try:
            _obs_server.maybe_start(
                cfg.metrics_port if cfg.is_set("metrics_port") else None)
        except OSError as e:
            log_warning(f"metrics endpoint could not start: {e}")

    def _load(m):
        return m if isinstance(m, Booster) else Booster(model_file=m)

    table = None if models is None else {n: _load(m)
                                         for n, m in models.items()}
    single = None if model is None else _load(model)
    kw = {}
    for name, param in (("max_wait_ms", "serve_max_wait_ms"),
                        ("max_queue", "serve_max_queue"),
                        ("slo_p99_ms", "serve_slo_p99_ms"),
                        ("tenant_quota", "serve_tenant_quota")):
        if cfg.is_set(param):
            kw[name] = getattr(cfg, param)
    fleet_kw = {}
    for name, param in (("replicas", "serve_replicas"),
                        ("deadline_ms", "serve_deadline_ms"),
                        ("hedge_ms", "serve_hedge_ms"),
                        ("retry_budget", "serve_retry_budget"),
                        ("trip", "serve_replica_trip"),
                        ("cooldown_ms", "serve_replica_cooldown_ms"),
                        ("hang_timeout_ms", "serve_hang_timeout_ms"),
                        ("restart_backoff_ms", "serve_restart_backoff_ms"),
                        ("max_restarts", "serve_max_restarts")):
        if cfg.is_set(param):
            fleet_kw[name] = getattr(cfg, param)
    if fleet_kw:
        return ServingFleet(single, models=table, start=start,
                            **kw, **fleet_kw)
    return ServingRuntime(single, models=table, start=start, **kw)


def continual_train(model=None, params: Optional[Dict[str, Any]] = None, *,
                    runtime=None, model_name: str = "default",
                    reference=None, state_dir: Optional[str] = None,
                    cache_path: Optional[str] = None,
                    start: bool = True, **runner_kwargs):
    """Continual-training entry point (README "Continuous training"):
    build — and by default START — a
    :class:`~lightgbm_tpu.continual.ContinualRunner` that ingests fresh
    data beside a live :class:`~lightgbm_tpu.serve.ServingRuntime`,
    periodically refits/appends on-device, and hot-swaps the serving
    ensemble with zero downtime.  The live ``/metrics`` + ``/healthz``
    endpoint comes up exactly as ``train``/``serve`` bring it up.

    ``model`` is a :class:`Booster` or model-file path; ``runtime`` an
    optional ServingRuntime already serving it under ``model_name``;
    ``reference`` the training Dataset (or its ``save_binary`` cache
    path) carrying the FROZEN bin mappers; ``params`` the policy knobs
    (``update_every_rows``, ``update_every_s``, ``append_trees``,
    ``drift_window``) plus the usual ``metrics_port=``/``telemetry=``.
    ``state_dir`` arms durable rollover checkpoints (+ ``resume=True``
    in ``runner_kwargs`` to pick the newest fleet-valid one up);
    ``cache_path`` arms the durable CRC'd ingest cache.

    >>> rt = lgb.serve(booster)
    >>> cr = lgb.continual_train(booster, {"update_every_rows": 4096},
    ...                          runtime=rt, reference=train_ds)
    """
    from .continual.runtime import ContinualRunner

    cfg = Config.from_dict(dict(params or {}))
    set_verbosity(cfg.verbosity)
    telemetry_on = (bool(cfg.telemetry) if cfg.is_set("telemetry")
                    else _obs.DEFAULT_ENABLED)
    _obs.set_enabled(telemetry_on)
    if telemetry_on:
        try:
            _obs_server.maybe_start(
                cfg.metrics_port if cfg.is_set("metrics_port") else None)
        except OSError as e:
            log_warning(f"metrics endpoint could not start: {e}")
    bst = model if isinstance(model, Booster) else Booster(model_file=model)
    for name in ("update_every_rows", "update_every_s", "append_trees",
                 "drift_window"):
        if cfg.is_set(name):
            runner_kwargs.setdefault(name, getattr(cfg, name))
    return ContinualRunner(bst, runtime=runtime, model_name=model_name,
                           reference=reference, state_dir=state_dir,
                           cache_path=cache_path, start=start,
                           **runner_kwargs)


def train_fleet(params: Optional[Dict[str, Any]], train_set, labels=None, *,
                num_boost_round: int = 100, weights=None, rounds=None):
    """Fleet-training entry point (README "Booster fleets"): train B
    independent k=1 boosters over ONE shared binned feature matrix as
    one donated dispatch per round
    (:class:`~lightgbm_tpu.models.fleet.FleetBooster`), instead of the
    host loop over :func:`train` that jaxlint R18 flags.

    ``train_set`` is either the shared :class:`Dataset` plus ``labels``
    as a (B, N) per-lane label matrix (optionally ``weights`` (B, N)),
    or a LIST of Datasets over identical feature data whose labels/
    weights are stacked here.  ``rounds`` optionally gives per-lane
    boosting budgets (device-side early stop; default
    ``num_boost_round`` everywhere).  ``params`` may pin ``fleet_size``
    as a shape guard (docs/Parameters.md).  Returns the trained
    :class:`FleetBooster`; per-lane :class:`Booster` handles come from
    its ``booster(b)`` / ``boosters()``.

    >>> fb = lgb.train_fleet({"num_leaves": 31}, ds, labels_bn)
    >>> fb.booster(3).predict(X)
    """
    from .models.fleet import FleetBooster, FleetError

    cfg = Config.from_dict(dict(params or {}))
    set_verbosity(cfg.verbosity)
    telemetry_on = (bool(cfg.telemetry) if cfg.is_set("telemetry")
                    else _obs.DEFAULT_ENABLED)
    _obs.set_enabled(telemetry_on)
    if telemetry_on:
        try:
            _obs_server.maybe_start(
                cfg.metrics_port if cfg.is_set("metrics_port") else None)
        except OSError as e:
            log_warning(f"metrics endpoint could not start: {e}")
    if isinstance(train_set, (list, tuple)):
        if labels is not None:
            raise FleetError(
                "train_fleet: pass EITHER a list of Datasets OR one "
                "Dataset + a (B, N) label matrix, not both")
        datasets = list(train_set)
        if not datasets:
            raise FleetError("train_fleet: empty Dataset list")
        labels = np.stack([np.asarray(d.label, np.float64)
                           for d in datasets])
        ws = [d.weight for d in datasets]
        if any(w is not None for w in ws):
            weights = np.stack([
                np.ones(labels.shape[1], np.float64) if w is None
                else np.asarray(w, np.float64) for w in ws])
        train_set = datasets[0]
    elif labels is None:
        raise FleetError(
            "train_fleet: a (B, N) label matrix (or a list of Datasets) "
            "is required")
    fb = FleetBooster(train_set, labels, params,
                      weights=weights, rounds=rounds)
    return fb.train(num_boost_round)


def _trace_path(cfg: Config) -> str:
    """The run's trace-export path: ``trace_file=`` when set, else the
    ``LGBMTPU_TRACE_FILE`` env spelling (the launcher sets a per-rank
    path so ``aggregate_fleet_trace`` can merge the fleet's files)."""
    return cfg.trace_file or os.environ.get("LGBMTPU_TRACE_FILE", "")


def _finish_run_report(cfg: Config) -> None:
    """End-of-run observability (docs/OBSERVABILITY.md): the reference-style
    "Time for X / counter = v" report through the logger (debug verbosity —
    the TIMETAG analogue, quiet by default), and the machine-readable
    snapshot to ``metrics_file=`` when configured (atomic JSON; render with
    ``python -m lightgbm_tpu.obs <file>``)."""
    if not _obs.enabled():
        for name, val in (("metrics_file", cfg.metrics_file),
                          ("trace_file", _trace_path(cfg))):
            if val:
                log_warning(f"{name}={val} ignored: telemetry is disabled "
                            "(telemetry=false / LGBMTPU_TELEMETRY=0)")
        return
    snap = _obs.snapshot()
    for line in _obs.render_lightgbm(snap):
        log_debug(line)
    if cfg.metrics_file:
        # best-effort: an unwritable metrics path must never cost the
        # caller a fully trained booster
        try:
            _obs.write_snapshot(cfg.metrics_file, snap)
        except OSError as e:
            log_warning(f"could not write metrics snapshot to "
                        f"{cfg.metrics_file}: {e}")
        else:
            log_info(f"Metrics snapshot written to {cfg.metrics_file}")
    trace_out = _trace_path(cfg)
    if trace_out:
        # Chrome-trace/Perfetto span export (obs/trace.py); same
        # best-effort contract as metrics_file
        try:
            n_spans = _trace.write_trace(trace_out)
        except OSError as e:
            log_warning(f"could not write trace to {trace_out}: {e}")
        else:
            log_info(f"Trace ({n_spans} spans) written to {trace_out}")
        # disarm the run's spill sink: evictions from LATER work in this
        # process (another train, serving) must not append to — and be
        # mistaken for — this run's span history
        _trace.disable_spill()


def _replay_scores(gbdt) -> None:
    """Recompute train scores from existing trees (continued training).
    The per-tree f32 adds run in training order, so a resume from a
    raw-delta snapshot reproduces the live score state bitwise
    (docs/ROBUSTNESS.md "Elastic fleet recovery")."""
    import numpy as _np

    import jax.numpy as jnp

    if (getattr(gbdt.train_set, "ooc_spill", False) and len(gbdt.models) > 1
            and all(t.num_cat == 0 for t in gbdt.models)):
        # spill regime: one stream sweep for the whole ensemble — a
        # per-tree replay would re-decompress the bin cache T times.
        # Categorical trees fall through to the per-tree loop below
        # (predict_leaf_binned_tree streams them host-chunk-wise): slower
        # (one sweep per tree) but a resume must never fail over it.
        _replay_scores_streamed(gbdt)
        return
    k = gbdt.num_tree_per_iteration
    for i, tree in enumerate(gbdt.models):
        c = i % k
        if tree.is_linear:
            # linear leaves carry per-leaf linear terms — a
            # leaf_value-only replay would silently drop them (mirror of
            # GBDT.add_valid's continued-training replay)
            vals = jnp.asarray(
                tree.predict_batch(_np.asarray(gbdt.train_set.raw_device)),
                jnp.float32)
        else:
            leaf = gbdt.train_set.predict_leaf_binned_tree(tree)
            vals = jnp.asarray(tree.leaf_value, jnp.float32)[leaf]
        if k == 1:
            gbdt._score = gbdt._score + vals
        else:
            gbdt._score = gbdt._score.at[:, c].add(vals)


def _replay_scores_streamed(gbdt) -> None:
    """Spill-regime replay: ONE sequential pass over the bin stream for
    ALL trees (Dataset.predict_leaf_binned_trees_chunked), folding each
    chunk's per-tree f32 leaf values into the score in training order —
    the same per-row add sequence as the tree-at-a-time replay, so the
    result stays bitwise while the cache is decompressed once instead of
    once per tree."""
    import numpy as _np

    import jax.numpy as jnp

    k = gbdt.num_tree_per_iteration
    trees = gbdt.models
    leaf_vals = [jnp.asarray(t.leaf_value, jnp.float32) for t in trees]
    parts = []
    for _row_lo, valid, leaf in gbdt.train_set.predict_leaf_binned_trees_chunked(trees):
        chunk = gbdt._score[_row_lo:_row_lo + valid] if k == 1 else \
            gbdt._score[_row_lo:_row_lo + valid, :]
        for i in range(len(trees)):
            vals = leaf_vals[i][leaf[i, :valid]]
            if k == 1:
                chunk = chunk + vals
            else:
                chunk = chunk.at[:, i % k].add(vals)
        parts.append(chunk)
    if parts:
        gbdt._score = jnp.concatenate(parts, axis=0)


class CVBooster:
    """reference: engine.py CVBooster — container of per-fold boosters."""

    def __init__(self, boosters: Optional[List[Booster]] = None):
        self.boosters = boosters or []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]

        return handler_function


def _make_n_folds(full_data: Dataset, nfold: int, params: Dict, seed: int,
                  stratified: bool, shuffle: bool):
    full_data.construct()
    num_data = full_data.num_data()
    rng = np.random.RandomState(seed)
    if full_data.group is not None:
        # ranking: folds must respect query boundaries (reference: cv's
        # _make_n_folds group-aware split)
        nq = len(full_data.group)
        qidx = np.arange(nq)
        if shuffle:
            rng.shuffle(qidx)
        bounds = np.concatenate([[0], np.cumsum(full_data.group)]).astype(np.int64)
        for q_chunk in np.array_split(qidx, nfold):
            te = np.concatenate([np.arange(bounds[q], bounds[q + 1]) for q in q_chunk])
            te = np.sort(te)
            tr = np.setdiff1d(np.arange(num_data), te)
            yield tr, te
        return
    if stratified and full_data.label is not None:
        label = np.asarray(full_data.label)
        classes = np.unique(label)
        folds = [[] for _ in range(nfold)]
        for c in classes:
            idx = np.nonzero(label == c)[0]
            if shuffle:
                rng.shuffle(idx)
            for i, chunk in enumerate(np.array_split(idx, nfold)):
                folds[i].extend(chunk.tolist())
        test_indices = [np.asarray(sorted(f), dtype=np.int64) for f in folds]
    else:
        idx = np.arange(num_data)
        if shuffle:
            rng.shuffle(idx)
        test_indices = [np.sort(chunk) for chunk in np.array_split(idx, nfold)]
    for te in test_indices:
        tr = np.setdiff1d(np.arange(num_data), te)
        yield tr, te


def cv(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    folds=None,
    nfold: int = 5,
    stratified: bool = True,
    shuffle: bool = True,
    metrics=None,
    feval=None,
    init_model=None,
    fpreproc=None,
    seed: int = 0,
    callbacks=None,
    eval_train_metric: bool = False,
    return_cvbooster: bool = False,
) -> Dict[str, Any]:
    """reference: engine.py cv()."""
    params = dict(params or {})
    if metrics is not None:
        params["metric"] = metrics
    params = choose_param_value("num_iterations", params, None)
    if params.get("num_iterations") is not None:
        num_boost_round = int(params["num_iterations"])
    params.pop("num_iterations", None)
    params = choose_param_value("early_stopping_round", params, None)
    early_stopping_round = params.get("early_stopping_round")
    objective = params.get("objective", "")
    stratified = stratified and isinstance(objective, str) and (
        objective.startswith("binary") or objective.startswith("multiclass")
    )

    train_set.construct()
    if folds is None:
        folds = list(_make_n_folds(train_set, nfold, params, seed, stratified, shuffle))
    elif hasattr(folds, "split"):
        folds = list(folds.split(np.zeros(train_set.num_data()), np.asarray(train_set.label)))

    cvbooster = CVBooster()
    fold_valid_sets = []
    for tr_idx, te_idx in folds:
        tr = train_set.subset(tr_idx)
        te = train_set.subset(te_idx)
        bst = Booster(params=params, train_set=tr)
        bst.add_valid(te, "valid")
        cvbooster.append(bst)
        fold_valid_sets.append(te)

    callbacks = list(callbacks or [])
    if early_stopping_round is not None and int(early_stopping_round) > 0:
        from .callback import early_stopping

        callbacks.append(early_stopping(int(early_stopping_round), verbose=False))
    for cb in callbacks:
        if not hasattr(cb, "order"):
            cb.order = 0  # type: ignore[attr-defined]
    cb_before = sorted([c for c in callbacks if getattr(c, "before_iteration", False)], key=lambda c: c.order)
    cb_after = sorted([c for c in callbacks if not getattr(c, "before_iteration", False)], key=lambda c: c.order)

    results: Dict[str, List[float]] = {}
    try:
        for i in range(num_boost_round):
            for cb in cb_before:
                cb(CallbackEnv(cvbooster, params, i, 0, num_boost_round, []))
            merged: Dict[tuple, List[float]] = {}
            for bst in cvbooster.boosters:
                bst.update()
                evals = bst.eval_valid(feval)
                if eval_train_metric:
                    evals = bst.eval_train(feval) + evals
                for (name, metric, val, hib) in evals:
                    merged.setdefault((name, metric, hib), []).append(val)
            agg = []
            for (name, metric, hib), vals in merged.items():
                mean, std = float(np.mean(vals)), float(np.std(vals))
                results.setdefault(f"{name} {metric}-mean", []).append(mean)
                results.setdefault(f"{name} {metric}-stdv", []).append(std)
                agg.append((name, metric, mean, hib, std))
            for cb in cb_after:
                cb(CallbackEnv(cvbooster, params, i, 0, num_boost_round, agg))
    except EarlyStopException as e:
        cvbooster.best_iteration = e.best_iteration + 1
        for k in list(results.keys()):
            results[k] = results[k][: cvbooster.best_iteration]
    if return_cvbooster:
        results["cvbooster"] = cvbooster  # type: ignore[assignment]
    return results
