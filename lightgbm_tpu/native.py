"""ctypes binding to the native C++ data loader (src/native/loader.cpp).

The .so is compiled lazily with g++ on first use and cached next to the
source (reference analogue: lib_lightgbm.so built by CMake; here the only
native stage is text parsing — see loader.cpp header).  Binding is plain
ctypes because pybind11 is not in this image (per environment constraints).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "src", "native")
_SRC = os.path.join(_NATIVE_DIR, "loader.cpp")
_SO = os.path.join(_NATIVE_DIR, "_loader.so")

_lock = threading.Lock()
_lib = None
_lib_failed = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-fPIC", "-shared", "-fopenmp", "-std=c++17",
        "-o", _SO, _SRC,
    ]
    from .utils.log import log_warning

    try:
        r = subprocess.run(cmd, capture_output=True, timeout=240, text=True)  # jaxlint: disable=L2 (one-time lazy .so build under the load lock; contending callers need the built library before they can proceed anyway)
        ok = r.returncode == 0 and os.path.exists(_SO)
        if not ok:
            log_warning(
                "native loader build failed (falling back to numpy parser):\n"
                + (r.stderr or "")[-2000:]
            )
        return ok
    except Exception as exc:
        log_warning(f"native loader build failed ({exc!r}); numpy fallback in use")
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native loader; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
            ):
                if not os.path.exists(_SRC) or not _build():
                    _lib_failed = True
                    return None
            lib = ctypes.CDLL(_SO)
            lib.lgbmtpu_parse_file.restype = ctypes.c_int
            lib.lgbmtpu_parse_file.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.lgbmtpu_free.argtypes = [ctypes.POINTER(ctypes.c_double)]
            _lib = lib
        except Exception:
            _lib_failed = True
    return _lib


_FORMAT_CODE = {"auto": -1, "csv": 0, "tsv": 1, "libsvm": 2}


def parse_file_native(
    path: str, fmt: str = "auto", has_header: bool = False, label_idx: int = 0
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Parse with the native loader; returns (data (N,F) f64, label (N,))
    or None when the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    pd = ctypes.POINTER(ctypes.c_double)()
    pl = ctypes.POINTER(ctypes.c_double)()
    n = ctypes.c_int64()
    f = ctypes.c_int64()
    rc = lib.lgbmtpu_parse_file(
        path.encode(), _FORMAT_CODE.get(fmt, -1), int(has_header), label_idx,
        ctypes.byref(pd), ctypes.byref(pl), ctypes.byref(n), ctypes.byref(f),
    )
    if rc != 0:
        return None
    try:
        data = np.ctypeslib.as_array(pd, shape=(n.value, f.value)).copy()
        label = np.ctypeslib.as_array(pl, shape=(n.value,)).copy()
    finally:
        lib.lgbmtpu_free(pd)
        lib.lgbmtpu_free(pl)
    return data, label
