"""The serving loop: continuous micro-batching of concurrent predicts
onto one warm executable (round 18; README "Serving").

Every serving PRIMITIVE predates this module — packed device-resident
ensembles (``GBDT._packed``), the pow-2 bucket ladder
(``_predict_bucket``), warm predict pinned at 1 dispatch + 1 accounted
sync, per-bucket latency reservoirs, ``/metrics`` + ``/healthz`` — but
each caller used to drive its own blocking predict, so K concurrent
requests cost K dispatches, K syncs and K host staging allocations.
This module is the PROCESS tying the primitives together, the
continuous-batching insight from LLM serving applied to tree ensembles:

* **Coalescing** — a request queue + coalescer thread packs concurrent
  requests for the same (model, raw/converted) group into the smallest
  covering bucket rung, with a ``serve_max_wait_ms`` admission window
  and an IMMEDIATE flush the moment a rung fills.  Rows are sliced back
  out per request; because rows traverse independently, conversions are
  rowwise, and bucket padding is pinned bit-identical, every coalesced
  response is BITWISE equal to the individual ``Booster.predict`` call
  it replaces (tests/test_serve.py).  The coalesced batch reuses an
  already-compiled bucket executable — zero retraces by construction.
* **Pinned, double-buffered staging** — one reused host buffer PAIR per
  bucket rung (the round-12 out-of-core reused-buffer discipline applied
  to serving: one copy per request into the shared batch buffer, never a
  fresh per-batch allocation — jaxlint R15 bans the anti-pattern), and a
  one-deep dispatch handoff so batch k+1 stages + uploads while batch k
  executes.  The dispatch itself goes through
  ``GBDT.predict_coalesced`` — the SAME jitted entries as the
  single-caller warm path (pinned by the ``predict_coalesced_bucket``
  jaxpr-audit contract), joining the accounted ``sync_pull`` protocol:
  ONE dispatch + ONE blocking sync per coalesced batch, telemetry and
  tracing on (tests/test_predict_budget.py).
* **Load shedding** — submissions past ``serve_max_queue``, past a
  tenant's ``serve_tenant_quota``, past the ``serve_slo_p99_ms`` SLO
  (driven off the existing warm-latency reservoirs, only under queue
  pressure), or while ``/healthz`` reports unhealthy are SHED with a
  typed :class:`Overloaded` error — counted, evented, ``/healthz``
  visible via the ``serve_shedding`` gauge, and never a hang.
* **Multi-model multi-tenant** — N packed ensembles resident behind one
  bucket ladder; each model name is a tenant (quota + latency labels).
  :meth:`ServingRuntime.swap_model` builds the replacement's pack BEFORE
  publishing it, and ``GBDT._packed``'s version key (bump-on-mutate, not
  null-on-mutate) keeps the previous pack servable for in-flight
  predicts — a hot swap never cools the cache.

This module owns NO jitted code: it may only stage, enqueue and dispatch
the existing accounted entries (pinned by tests/test_serve.py's AST
check) — the whole point is that the serving loop cannot grow a second
executable family.
"""

from __future__ import annotations

import threading
import time
from queue import Queue
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..basic import Booster, LightGBMError
from ..models.gbdt import _predict_bucket
from ..obs import metrics as _obs
from ..utils import faults as _flt
from ..utils import locktrace as _lt
from ..obs import server as _obs_server
from ..obs import trace as _trace

# one coalesced batch never exceeds this many rows (the top rung the
# coalescer will fill; single requests larger than this still serve, as
# their own batch through the ordinary ladder)
MAX_BATCH_ROWS = 4096
# SLO/health shed-state recompute cadence: percentile + health derivation
# sort reservoirs and walk counters, so the verdict is cached briefly
# instead of recomputed per request
_SHED_REFRESH_S = 0.05


class Overloaded(LightGBMError):
    """A submission the runtime REFUSED (queue bound, tenant quota, p99
    SLO, or unhealthy process) — the typed, immediate alternative to an
    unbounded queue.  ``reason`` is the shed cause
    (``queue_full`` / ``tenant_quota`` / ``slo_p99`` / ``unhealthy``)."""

    def __init__(self, reason: str, tenant: str):
        super().__init__(
            f"serving runtime shed the request (reason={reason}, "
            f"tenant={tenant}) — see serve_shed_total / the serve_shed "
            "event stream")
        self.reason = reason
        self.tenant = tenant


class DeadlineExceeded(LightGBMError):
    """A request that was ADMITTED but missed its ``serve_deadline_ms``
    budget — typed distinctly from :class:`Overloaded` (which is an
    admission refusal): the caller's SLA logic treats "never started"
    and "started but late" differently, and the ``/predict`` front door
    maps them to 429 vs 504."""

    def __init__(self, tenant: str, deadline_ms: float):
        super().__init__(
            f"serving request exceeded its {deadline_ms:g} ms deadline "
            f"(tenant={tenant}) — admission succeeded, completion was "
            "late; see serve_deadline_exceeded_total")
        self.tenant = tenant
        self.deadline_ms = deadline_ms


# /predict requests are bounded even when no deadline is configured: an
# HTTP worker must never wedge on a result() wait
_PREDICT_HTTP_TIMEOUT_S = 30.0
_PREDICT_MAX_BODY = 32 << 20


class _Request:
    """One queued predict: host rows + completion event.  ``x`` is
    already cast to f64 (mirroring ``Booster.predict``'s intake cast, so
    the staged f32 batch holds the same bits an individual call would).

    ``ctx`` is the request's :class:`~..obs.trace.TraceContext` — minted
    at admission, carried EXPLICITLY on the request across the
    coalescer/dispatcher/replica thread handoffs (a thread-local stack
    cannot follow them), so every span the request's journey emits files
    under one trace id.  The ``t_*`` stamps are host ``perf_counter``
    reads at points the pipeline already touches; the completion path
    turns them into the queue/coalesce/staging/dispatch/sliceout phase
    breakdown (zero new device pulls — the R9/R10 rule)."""

    __slots__ = ("x", "n", "model", "raw", "serial", "event", "result",
                 "error", "t0", "t_done", "deadline", "retries", "avoid",
                 "ctx", "t_dequeue", "t_stage", "t_hand")

    def __init__(self, x: np.ndarray, model: str, raw: bool,
                 deadline: Optional[float] = None,
                 ctx: Optional[_trace.TraceContext] = None):
        self.x = x
        self.n = int(x.shape[0])
        self.model = model
        self.raw = raw
        self.serial = False
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.t0 = time.perf_counter()
        self.t_done: Optional[float] = None  # stamped at completion —
        # open-loop harnesses read t_done - t0 for true request latency
        # fleet-layer fields (serve/fleet.py): absolute monotonic deadline,
        # the exactly-once requeue count, and the replica index a retried
        # request must route AWAY from
        self.deadline = deadline
        self.retries = 0
        self.avoid = -1
        self.ctx = ctx
        # phase stamps (perf_counter): first coalescer pop, staging
        # start, staged-and-uploaded.  A requeued/hedged request is
        # re-stamped by its winning leg — the breakdown describes the
        # journey that actually delivered the bits.
        self.t_dequeue: Optional[float] = None
        self.t_stage: Optional[float] = None
        self.t_hand: Optional[float] = None


def _phase_breakdown(r: "_Request", t_sync: Optional[float],
                     now: float) -> Dict[str, float]:
    """Per-request phase milliseconds from the host stamps the pipeline
    already takes — queue (admission→first pop), coalesce (pop→staging
    start), staging (pack+upload issue), dispatch (hand wait + device
    execute through the accounted sync), sliceout (sync→publish).  A
    missing stamp (serial requests skip staging; a failed dispatch never
    syncs) collapses its phase to zero rather than guessing."""
    t_dq = r.t_dequeue if r.t_dequeue is not None else r.t0
    t_st = r.t_stage if r.t_stage is not None else t_dq
    t_hd = r.t_hand if r.t_hand is not None else t_st
    t_sy = t_sync if t_sync is not None else now
    return {"queue": max(t_dq - r.t0, 0.0) * 1e3,
            "coalesce": max(t_st - t_dq, 0.0) * 1e3,
            "staging": max(t_hd - t_st, 0.0) * 1e3,
            "dispatch": max(t_sy - t_hd, 0.0) * 1e3,
            "sliceout": max(now - t_sy, 0.0) * 1e3}


def _unwrap(model) -> Any:
    """Booster -> its GBDT; a GBDT passes through (the bench harness
    builds synthetic GBDTs directly)."""
    return model._gbdt if isinstance(model, Booster) else model


class ServingRuntime:
    """In-process async serving over one or more trained models.

    >>> rt = ServingRuntime(booster, max_wait_ms=2.0)
    >>> with rt:
    ...     y = rt.predict(X)                  # blocking, coalesced
    ...     h = rt.submit(X2); y2 = rt.result(h)   # async pair

    Construction does not start threads unless ``start=True`` (the
    default); an unstarted runtime still queues submissions, which drain
    on :meth:`start` — the deterministic harness tests and the open-loop
    bench build on.  Defaults for the knobs come from the first model's
    Config (``serve_max_wait_ms`` / ``serve_max_queue`` /
    ``serve_slo_p99_ms`` / ``serve_tenant_quota``); explicit kwargs win.
    ``shed_unhealthy=False`` opts out of health-driven shedding (the
    process-cumulative health counters may reflect unrelated earlier
    work, e.g. in a shared test process).
    """

    def __init__(self, model=None, *, models: Optional[Dict[str, Any]] = None,
                 max_wait_ms: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 slo_p99_ms: Optional[float] = None,
                 tenant_quota: Optional[int] = None,
                 shed_unhealthy: bool = True,
                 start: bool = True):
        if (model is None) == (models is None):
            raise LightGBMError(
                "ServingRuntime needs exactly one of model= (single) or "
                "models= (a {name: Booster} table)")
        table = {"default": model} if models is None else dict(models)
        if not table:
            raise LightGBMError("ServingRuntime needs at least one model")
        # the model TABLE (name -> GBDT) — deliberately not "_models",
        # which names the per-ensemble TREE LIST whose in-place mutation
        # jaxlint R16 polices in serve/continual code
        self._table: Dict[str, Any] = {n: _unwrap(m)
                                       for n, m in table.items()}
        cfg = next(iter(self._table.values())).cfg
        self._max_wait_s = (float(cfg.serve_max_wait_ms) if max_wait_ms is None
                            else float(max_wait_ms)) / 1e3
        self._max_queue = (int(cfg.serve_max_queue) if max_queue is None
                           else int(max_queue))
        self._slo_p99_ms = (float(cfg.serve_slo_p99_ms) if slo_p99_ms is None
                            else float(slo_p99_ms))
        self._tenant_quota = (int(cfg.serve_tenant_quota)
                              if tenant_quota is None else int(tenant_quota))
        self._shed_unhealthy = bool(shed_unhealthy)
        # request deadline in seconds; 0 disables.  The base runtime never
        # sets it — the fleet layer (serve/fleet.py) does, and stamps every
        # admitted request via submit()'s _Request construction.
        self._deadline_s = 0.0

        self._cv = _lt.condition("serve.cv")
        self._queue: List[_Request] = []
        self._queued_per_tenant: Dict[str, int] = {}
        # depth-1 handoff: the coalescer blocks here while the dispatcher
        # is one batch behind — the one-deep double-buffered device feed
        self._hand: Queue = Queue(maxsize=1)
        # (nb, f) -> free-list of pinned (rows, mask) pairs (two per
        # rung).  A pair is checked OUT at staging and returned by the
        # dispatcher only after the batch's accounted sync retired —
        # this is what makes reuse safe even where jax.device_put
        # zero-copy ALIASES the host buffer (the CPU backend does:
        # mutating the numpy source after device_put mutates the device
        # array), so a toggle scheme keyed on batch parity would corrupt
        # an in-flight batch under sustained load
        self._staging: Dict[Tuple[int, int], Queue] = {}
        # every ADMITTED, unresolved request (added in submit under _cv,
        # discarded when its event is set).  stop()'s drain sweep walks
        # this — NOT just self._queue — so a request a worker popped but
        # never resolved (a dispatch wedged inside the device runtime)
        # still gets a typed error instead of hanging its waiter forever
        self._pending: set = set()
        self._shed_cache: Tuple[float, Optional[str]] = (-1e9, None)
        self._running = False
        self._started = False
        self._closed = False
        self._coalescer: Optional[threading.Thread] = None
        self._dispatcher: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ServingRuntime":
        # state flips under _cv: stop() reads/writes _running/_closed
        # under the same lock, and the under-lock _started check makes
        # concurrent start() calls spawn exactly one thread pair (the
        # unlocked version was an L3 finding plus a double-spawn TOCTOU)
        with self._cv:
            if self._closed:
                raise LightGBMError("ServingRuntime is stopped")
            if self._started:
                return self
            self._started = True
            self._running = True
        self._spawn_workers()
        # the /predict front door: the most recently started runtime owns
        # the route on the (singleton) metrics endpoint — obs stays
        # stdlib-only, so the serve layer registers a callable instead of
        # obs importing serve
        _obs_server.set_predict_handler(self._http_predict)
        _obs.event("serve_start", models=sorted(self._table),
                   max_wait_ms=self._max_wait_s * 1e3,
                   max_queue=self._max_queue)
        return self

    def _spawn_workers(self) -> None:
        """Spawn the worker threads (overridden by ServingFleet, which
        runs one dispatcher per replica plus a supervisor)."""
        self._coalescer = threading.Thread(  # jaxlint: disable=L5 (joined via the _worker_threads() loop in stop())
            target=self._coalesce_loop, daemon=True, name="lgbmtpu-coalescer")
        self._dispatcher = threading.Thread(  # jaxlint: disable=L5 (joined via the _worker_threads() loop in stop())
            target=self._dispatch_loop, daemon=True, name="lgbmtpu-dispatch")
        self._dispatcher.start()
        self._coalescer.start()

    def _worker_threads(self) -> List[threading.Thread]:
        """Every thread stop() must join (fleet adds replicas + the
        supervisor)."""
        return [t for t in (self._coalescer, self._dispatcher)
                if t is not None]

    def stop(self) -> None:
        """Drain the queue, then stop the worker threads.  Idempotent;
        never abandons an accepted request: after the joins, EVERY
        admitted request whose event is still unset — still queued,
        or popped by a worker that wedged mid-dispatch and will never
        publish a result — is failed with a typed error.  (The old
        sweep only failed ``self._queue``; a batch a wedged dispatcher
        held was in neither list, and its waiters hung forever — the
        stop-under-load test in tests/test_serve.py pins the fix.)"""
        with self._cv:
            if self._closed:
                return
            # closed + drained under ONE lock section: a submit racing
            # this either raised on the under-lock _closed check or its
            # request is already visible to the draining coalescer
            self._closed = True
            self._running = False
            self._cv.notify_all()
        _obs_server.clear_predict_handler(self._http_predict)
        wedged = False
        if self._started:
            for t in self._worker_threads():
                t.join(timeout=30)
                wedged = wedged or t.is_alive()
        # the drain sweep: anything admitted but unresolved gets a typed
        # error NOW.  After a clean join this set is empty (the coalescer
        # drains the queue and the dispatcher resolves every handed batch
        # before exiting); it is non-empty only for a never-started
        # runtime or a wedged worker.
        with self._cv:
            leftover = [r for r in self._pending if not r.event.is_set()]
            self._pending.clear()
            self._queue = []
            self._queued_per_tenant.clear()
        for r in leftover:
            r.error = LightGBMError(
                "ServingRuntime stopped before the request resolved "
                + ("(wedged worker thread)" if wedged
                   else "(runtime never started)" if not self._started
                   else "(shutdown drain)"))
            r.event.set()
        if leftover:
            _obs.event("serve_stop_wedged" if wedged else "serve_stop_drain",
                       failed_requests=len(leftover))
        _obs.gauge("serve_queue_depth").set(0.0)
        _obs.event("serve_stop")

    def __enter__(self) -> "ServingRuntime":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- model table -----------------------------------------------------
    def models(self) -> List[str]:
        with self._cv:
            return sorted(self._table)

    def add_model(self, name: str, model) -> None:
        g = _unwrap(model)
        g._packed(0, -1)  # resident before the first request hits it
        with self._cv:
            if name in self._table:
                raise LightGBMError(
                    f"model {name!r} already served — use swap_model")
            self._table[name] = g

    def swap_model(self, name: str, model) -> None:
        """Hot-swap a served ensemble: the replacement's pack is built
        device-resident BEFORE publication, and in-flight batches keep
        the old GBDT's (versioned) pack — no request ever observes a
        cold cache (tests/test_serve.py pins this)."""
        g = _unwrap(model)
        if name not in self._table:
            raise LightGBMError(f"model {name!r} is not served")
        g._packed(0, -1)  # warm the new pack outside the serving path
        # chaos site: a failure BETWEEN the warm build and the table
        # publish must leave every replica serving the OLD ensemble —
        # the swap either fully publishes or changes nothing
        _flt.maybe_fail("swap_publish")
        with self._cv:
            self._table[name] = g
        _obs.counter("serve_model_swaps_total").inc()
        _obs.event("serve_model_swap", model=name)

    # -- client API ------------------------------------------------------
    def predict(self, X, *, model: str = "default", raw_score: bool = False,
                timeout: Optional[float] = None,
                trace_ctx: Optional[_trace.TraceContext] = None) -> np.ndarray:
        """Blocking coalesced predict — semantics (and bits) of
        ``Booster.predict(X, raw_score=raw_score)``.  Raises
        :class:`Overloaded` when shed, ``TimeoutError`` past
        ``timeout`` seconds."""
        return self.result(self.submit(X, model=model, raw_score=raw_score,
                                       trace_ctx=trace_ctx),
                           timeout=timeout)

    def submit(self, X, *, model: str = "default",
               raw_score: bool = False,
               trace_ctx: Optional[_trace.TraceContext] = None) -> _Request:
        """Enqueue one request (admission control happens HERE — a shed
        raises immediately, an accepted request always resolves).
        Returns a handle for :meth:`result`.

        ``trace_ctx`` is the request's trace identity when the caller
        (the HTTP front door, honoring an inbound ``traceparent``)
        already minted one; otherwise a fresh root context is minted
        here — admission is the single sampling decision point."""
        g = self._table.get(model)
        if g is None:
            raise LightGBMError(f"model {model!r} is not served "
                                f"(have {sorted(self._table)})")
        X = np.asarray(X, dtype=np.float64)  # Booster.predict's intake cast
        if X.ndim == 1:
            X = X[None, :]
        # the SLO/health verdict refresh snapshots the registry (sorts
        # reservoirs, runs collectors) — computed OUTSIDE the condition
        # lock so a refresh never stalls the coalescer's bookkeeping or
        # concurrent submits; the cached tuple is read under the lock
        self._refresh_shed_state()
        shed: Optional[str] = None
        req: Optional[_Request] = None
        with self._cv:
            # _closed re-checked UNDER the lock: a submit racing stop()
            # must either be failed here or be visible to the draining
            # coalescer — never appended after the drain finished
            if self._closed:
                raise LightGBMError("ServingRuntime is stopped")
            if len(self._queue) >= self._max_queue:
                shed = "queue_full"
            elif (self._tenant_quota > 0 and self._queued_per_tenant.get(
                    model, 0) >= self._tenant_quota):
                shed = "tenant_quota"
            else:
                shed = self._shed_cache[1]
                if shed == "slo_p99" and not self._queue:
                    # SLO shedding only under queue pressure — a lone
                    # request after a slow spell must serve, or the
                    # cumulative p99 could latch the runtime shut
                    shed = None
            if shed is None:
                req = _Request(X, model, bool(raw_score),
                               deadline=(time.monotonic() + self._deadline_s
                                         if self._deadline_s > 0 else None),
                               ctx=(trace_ctx if trace_ctx is not None
                                    else _trace.mint_request_context()))
                self._queue.append(req)
                self._pending.add(req)
                self._queued_per_tenant[model] = (
                    self._queued_per_tenant.get(model, 0) + 1)
                _obs.gauge("serve_queue_depth").set(len(self._queue))
                self._cv.notify_all()
            self._publish_shed_gauge()
        if shed is not None:
            _obs.counter("serve_shed_total").inc()
            _obs.counter(_obs.labeled("serve_shed_total",
                                      tenant=model)).inc()
            _obs.event("serve_shed", reason=shed, tenant=model,
                       rows=int(X.shape[0]))
            raise Overloaded(shed, model)
        _obs.counter("serve_requests_total").inc()
        _obs.counter(_obs.labeled("serve_requests_total",
                                  tenant=model)).inc()
        return req

    def result(self, req: _Request,
               timeout: Optional[float] = None) -> np.ndarray:
        if req.deadline is not None:
            budget = req.deadline - time.monotonic()
            if timeout is not None:
                budget = min(budget, timeout)
            if not req.event.wait(max(budget, 0.0)):
                if time.monotonic() >= req.deadline:
                    self._count_deadline(req.model)
                    raise DeadlineExceeded(req.model, self._deadline_s * 1e3)
                raise TimeoutError("serving request did not complete in "
                                   f"{timeout}s (queue depth "
                                   f"{len(self._queue)})")
        elif not req.event.wait(timeout):
            raise TimeoutError("serving request did not complete in "
                               f"{timeout}s (queue depth "
                               f"{len(self._queue)})")
        if req.error is not None:
            raise req.error
        return req.result

    @staticmethod
    def _count_deadline(tenant: str) -> None:
        _obs.counter("serve_deadline_exceeded_total").inc()
        _obs.counter(_obs.labeled("serve_deadline_exceeded_total",
                                  tenant=tenant)).inc()
        _obs.event("serve_deadline", tenant=tenant)

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            return {"queue_depth": len(self._queue),
                    "models": sorted(self._table),
                    "staging_rungs": sorted(k[0] for k in self._staging),
                    "running": self._running}

    # -- shedding --------------------------------------------------------
    def _refresh_shed_state(self) -> None:
        """Recompute the cached SLO/health shed verdict at most every
        _SHED_REFRESH_S.  Runs WITHOUT self._cv (the registry snapshot
        and reservoir percentile are the expensive part); the cache is a
        single tuple publish, safe to read under the lock.  Concurrent
        refreshes are harmless (same verdict, last write wins)."""
        now = time.monotonic()
        if now - self._shed_cache[0] < _SHED_REFRESH_S:
            return
        reason = None
        if self._slo_p99_ms > 0:
            p99 = _obs.histogram("predict_warm_latency_ms").percentile(99)
            if p99 is not None and p99 > self._slo_p99_ms:
                reason = "slo_p99"
        if reason is None and self._shed_unhealthy:
            code, _body = _obs_server.health()
            if code == 503:
                reason = "unhealthy"
        self._shed_cache = (now, reason)

    def _shedding_now(self) -> bool:
        """CURRENT shed state, derived from live queue/tenant/SLO state
        (under self._cv) — not a latch toggled per submission, so an
        idle drained runtime reads healthy and a tenant still at quota
        keeps /healthz degraded even while other tenants serve."""
        if len(self._queue) >= self._max_queue:
            return True
        if self._tenant_quota > 0 and any(
                v >= self._tenant_quota
                for v in self._queued_per_tenant.values()):
            return True
        reason = self._shed_cache[1]
        if reason == "unhealthy":
            return True
        return reason == "slo_p99" and bool(self._queue)

    def _publish_shed_gauge(self) -> None:
        """Under self._cv: recompute the /healthz-driving gauge from
        current state (obs/server.py DEGRADED_GAUGES)."""
        _obs.gauge("serve_shedding").set(
            1.0 if self._shedding_now() else 0.0)

    # -- coalescer -------------------------------------------------------
    def _coalesce_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and self._running:
                    self._cv.wait(0.1)
                if not self._queue:
                    break  # stopped and drained
                first = self._queue.pop(0)
                self._note_dequeued(first)
            # the caller owns the batch list: if ANYTHING below raises
            # (a pack build in _coalescible, a device OOM in device_put),
            # every already-popped request is failed loudly and the
            # thread keeps serving — a dead coalescer would turn every
            # future predict() into the unbounded hang the Overloaded
            # machinery exists to prevent
            batch: List[_Request] = [first]
            try:
                g = self._build_batch(first, batch)
                self._stage_and_hand(g, batch)
            except BaseException as e:  # noqa: BLE001
                for r in batch:
                    r.error = e
                    r.event.set()
                with self._cv:
                    for r in batch:
                        self._pending.discard(r)
        self._shutdown_pipeline()

    def _shutdown_pipeline(self) -> None:
        """Coalescer exit: wake the dispatch side (overridden by the
        fleet, whose replica loops poll ``self._running`` instead)."""
        self._hand.put(None)  # dispatcher stop sentinel

    def _note_dequeued(self, req: _Request) -> None:
        """Under self._cv: tenant + depth bookkeeping for one pop."""
        req.t_dequeue = time.perf_counter()  # queue-wait phase closes here
        left = self._queued_per_tenant.get(req.model, 1) - 1
        self._queued_per_tenant[req.model] = max(left, 0)
        _obs.gauge("serve_queue_depth").set(len(self._queue))
        # draining clears the shed state without waiting for a submit
        self._publish_shed_gauge()

    def _build_batch(self, first: _Request, batch: List[_Request]):
        """Admission: gather requests compatible with ``first`` (same
        model, same raw/converted group, same feature width).  The batch
        flushes the moment a pow-2 rung fills exactly, MAX_BATCH_ROWS is
        reached, or — the continuous-batching rule — the dispatch
        pipeline is IDLE: waiting for companions while the device sits
        empty only adds latency, whereas a busy pipeline grows the batch
        for free (new arrivals queue while batch k executes).  The
        ``serve_max_wait_ms`` window bounds the busy-pipeline wait.

        Fills the caller-owned ``batch`` list (so an exception cannot
        strand a popped request) and returns the resolved model — it
        rides along so a concurrent ``swap_model`` between eligibility
        check and staging cannot hand the batch a model it was not
        built against."""
        g = self._table.get(first.model)
        if g is None or not g._coalescible(first.raw):
            first.serial = True
            _obs.counter("serve_uncoalesced_total").inc()
            return g
        total = first.n
        f = first.x.shape[1]
        deadline = time.monotonic() + self._max_wait_s
        with self._cv:
            while True:
                took = True
                while took and total < MAX_BATCH_ROWS:
                    took = False
                    for i, r in enumerate(self._queue):
                        if (r.model == first.model and r.raw == first.raw
                                and r.x.shape[1] == f
                                and total + r.n <= MAX_BATCH_ROWS):
                            batch.append(self._queue.pop(i))
                            self._note_dequeued(r)
                            total += r.n
                            took = True
                            break
                if (total >= MAX_BATCH_ROWS
                        or total == _predict_bucket(total)
                        or self._pipeline_idle()):
                    break  # rung filled, cap reached, or idle pipeline
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._running:
                    break
                self._cv.wait(remaining)
        return g

    def _checkout_staging(self, nb: int, f: int):
        """Check a pinned (rows, mask) pair OUT of rung ``nb``'s
        free-list — allocated once (two pairs per rung, the double
        buffer), then recycled through :meth:`_return_staging` when the
        owning batch's accounted sync has retired.  Blocks when both
        pairs are in flight (a >2-deep pipeline cannot form anyway: the
        depth-1 handoff bounds it), which is precisely the discipline
        that keeps reuse safe under zero-copy ``device_put`` aliasing."""
        key = (nb, f)
        pool = self._staging.get(key)
        if pool is None:
            pool = Queue()
            for _ in range(self._staging_pairs()):
                pool.put((np.zeros((nb, f), np.float32),
                          np.zeros(nb, bool)))
            self._staging[key] = pool
        return key, pool.get()

    def _staging_pairs(self) -> int:
        """Pinned pairs per rung: 2 (the double buffer) for the solo
        runtime; the fleet sizes it replicas+1 so N concurrent in-flight
        batches on one rung cannot starve the coalescer."""
        return 2

    def _pipeline_idle(self) -> bool:
        """True when the dispatch side has fully retired its work — the
        coalescer's immediate-flush condition (overridden by the fleet:
        idle means ANY routable replica is idle)."""
        return self._hand.unfinished_tasks == 0

    def _return_staging(self, key, pair) -> None:
        self._staging[key].put(pair)

    def _stage_and_hand(self, g, batch: List[_Request]) -> None:
        """Pack the batch into the rung's pinned buffer (ONE copy per
        request), upload, and hand to the dispatcher.  The blocking
        depth-1 put is the pipeline: this upload overlaps the previous
        batch's device execution.  (The fleet overrides this to ROUTE
        the staged item to a healthy replica's hand queue.)"""
        if batch[0].serial:
            self._hand.put(("serial", batch, g))
            return
        self._hand.put(self._stage_batch(g, batch))

    def _stage_batch(self, g, batch: List[_Request]):
        """Stage one coalesced batch into a checked-out pinned pair and
        return the ``("batch", batch, payload)`` hand item.  On ANY
        failure the pair is returned before re-raising: leaking it would
        shrink the rung's pool and eventually block _checkout_staging
        forever — wedging the coalescer, the hang this module exists to
        prevent.  (After a successful hand-off the DISPATCHER owns the
        return.)"""
        total = sum(r.n for r in batch)
        nb = _predict_bucket(total)
        t_stage = time.perf_counter()  # coalesce-wait phase closes here
        for r in batch:
            r.t_stage = t_stage
        skey, pair = self._checkout_staging(nb, batch[0].x.shape[1])
        try:
            buf, mask = pair
            off = 0
            for r in batch:
                buf[off:off + r.n] = r.x  # f64->f32, same bits as _pad_rows
                off += r.n
            buf[off:] = 0.0
            mask[:off] = True
            mask[off:] = False
            x_dev = jax.device_put(buf)
            active = None if off == nb else jax.device_put(mask)
            t_hand = time.perf_counter()  # staged + uploaded (async): the
            for r in batch:              # staging phase closes here
                r.t_hand = t_hand
            return ("batch", batch, (g, x_dev, active, total, nb, skey, pair))
        except BaseException:
            self._return_staging(skey, pair)
            raise

    # -- dispatcher ------------------------------------------------------
    @staticmethod
    def _batch_ctx(batch: List[_Request]) -> Optional[_trace.TraceContext]:
        """Identity for one dispatch leg's span: a SIBLING of the first
        sampled member's context — same trace, NO parent edge.  The N
        member request spans each carry a link TO this context instead
        (the N-to-1 fan-in the coalescer creates cannot be expressed as
        parentage: a span has one parent, a batch has N requests)."""
        for r in batch:
            if r.ctx is not None and r.ctx.sampled:
                return r.ctx.sibling()
        return None

    def _finish_request(self, r: _Request, now: float,
                        t_sync: Optional[float],
                        leg_ctx: Optional[_trace.TraceContext] = None,
                        outcome: str = "ok",
                        replica: Optional[int] = None) -> None:
        """Completion bookkeeping for ONE resolved request: stamp
        ``t_done``, feed the latency + per-phase reservoirs (the latency
        reservoir keeps this trace_id as its exemplar when sampled),
        emit the ``serve.request`` span linked to the dispatch leg that
        delivered the bits, and wake the waiter LAST.  Shared by the
        solo dispatcher and the fleet's publish paths so every leg
        speaks the same span vocabulary.  Host-side arithmetic only —
        zero device pulls (the R9/R10 contract)."""
        r.t_done = now
        dt_ms = (now - r.t0) * 1e3
        sampled = r.ctx is not None and r.ctx.sampled
        _obs.histogram("serve_request_latency_ms").observe(
            dt_ms, exemplar=(r.ctx.trace_id if sampled else None))
        _obs.histogram(_obs.labeled(
            "serve_request_latency_ms", tenant=r.model)).observe(dt_ms)
        phases = _phase_breakdown(r, t_sync, now)
        for ph, v in phases.items():
            _obs.histogram(_obs.labeled(
                "serve_phase_ms", phase=ph)).observe(v)
        if sampled:
            attrs: Dict[str, Any] = {
                f"{ph}_ms": round(v, 3) for ph, v in phases.items()}
            if replica is not None:
                attrs["replica"] = replica
            _trace.record_span(
                "serve.request", now - r.t0, ctx=r.ctx,
                links=([leg_ctx] if leg_ctx is not None else None),
                model=r.model, rows=r.n, outcome=outcome,
                attempt=r.retries, **attrs)
        r.event.set()

    def _dispatch_loop(self) -> None:
        while True:
            item = self._hand.get()
            if item is None:
                self._hand.task_done()
                return
            kind, batch, payload = item
            t_batch = time.perf_counter()
            # the dispatch-leg span identity is minted BEFORE execution
            # and carried explicitly — this dispatcher thread's ambient
            # span stack is empty and must stay out of parentage (the
            # cross-thread bug R21 now lints for)
            leg_ctx = self._batch_ctx(batch)
            t_sync: Optional[float] = None
            outcome = "ok"
            staging = None
            try:
                if kind == "serial":
                    (r,) = batch
                    g = payload if payload is not None \
                        else self._table[r.model]
                    r.result = g.predict(r.x, raw_score=r.raw)
                    t_sync = time.perf_counter()
                else:
                    g, x_dev, active, total, nb, skey, pair = payload
                    staging = (skey, pair)
                    convert = ((not batch[0].raw)
                               and g.objective is not None)
                    res = g.predict_coalesced(x_dev, active, total,
                                              convert=convert,
                                              trace_ctx=leg_ctx)
                    # the accounted sync retired inside predict_coalesced
                    # — the dispatch phase closes on this host stamp
                    t_sync = time.perf_counter()
                    off = 0
                    for r in batch:
                        r.result = res[off:off + r.n]
                        off += r.n
                    _obs.counter("serve_batches_total").inc()
                    _obs.counter("serve_coalesced_rows_total").inc(total)
                    _obs.histogram("serve_batch_occupancy").observe(
                        total / nb)
            except BaseException as e:  # noqa: BLE001 — a failed batch
                outcome = "error"
                for r in batch:  # must fail its requests, not the thread
                    r.error = e
            finally:
                # the batch's sync has retired (or it failed): its
                # pinned pair may be reused — only now is mutation safe
                # under zero-copy device_put aliasing
                if staging is not None:
                    self._return_staging(*staging)
                # latency closes AFTER predict_coalesced's accounted
                # sync_pull — the device queue has provably drained, so
                # the reservoir is honest (the jaxlint-R9 contract)
                now = time.perf_counter()
                for r in batch:
                    self._finish_request(r, now, t_sync, leg_ctx, outcome)
                # leg_ctx is None exactly when NO member was sampled —
                # the admission-time decision covers the batch span too
                # (an identityless record would leak spans under
                # trace_sample=0)
                if leg_ctx is not None:
                    _trace.record_span(
                        "serve.batch", now - t_batch, ctx=leg_ctx,
                        requests=len(batch),
                        rows=sum(r.n for r in batch),
                        model=batch[0].model,
                        coalesced=kind == "batch", outcome=outcome,
                        attempt=0)
                # unfinished_tasks drops to 0 only here: the coalescer's
                # idle-pipeline flush reads it, so "idle" honestly means
                # the previous batch has fully retired (sync included) —
                # and the notify wakes a window-waiting coalescer so the
                # admission window stays a busy-pipeline-only cost
                self._hand.task_done()
                with self._cv:
                    for r in batch:
                        self._pending.discard(r)
                    self._cv.notify_all()


    # -- /predict front door (obs/server.py owns the socket) -------------
    def _http_predict(self, payload: Dict[str, Any],
                      traceparent: Optional[str] = None,
                      ) -> Tuple[int, Dict, Optional[str]]:
        """One ``POST /predict`` request: JSON rows in, predictions out,
        routed through the SAME submit/result path every other caller
        uses — so shedding, deadlines and fleet health apply unchanged,
        mapped onto HTTP: Overloaded -> 429 (unhealthy -> 503),
        DeadlineExceeded/timeout -> 504, stopped runtime -> 503, bad
        request -> 400.

        The request's trace context is minted HERE, honoring an inbound
        W3C ``traceparent`` (the caller's trace adopts our spans); the
        outbound header and the ``trace_id`` body field are returned on
        EVERY outcome — a shed or timed-out request is exactly the one
        the caller needs to look up."""
        _obs.counter("serve_http_requests_total").inc()
        ctx = _trace.mint_request_context(traceparent)
        tp_out = _trace.format_traceparent(ctx)

        def _done(code: int, body: Dict) -> Tuple[int, Dict, Optional[str]]:
            body["trace_id"] = ctx.trace_id
            return code, body, tp_out

        try:
            rows = payload.get("rows") if isinstance(payload, dict) else None
            if rows is None:
                return _done(400, {"error": "bad_request",
                                   "detail": 'body must be JSON like '
                                             '{"rows": [[...], ...], '
                                             '"model": "default", '
                                             '"raw_score": false}'})
            X = np.asarray(rows, dtype=np.float64)
            model = str(payload.get("model", "default"))
            raw = bool(payload.get("raw_score", False))
            y = self.predict(X, model=model, raw_score=raw,
                             timeout=_PREDICT_HTTP_TIMEOUT_S,
                             trace_ctx=ctx)
            return _done(200, {"model": model,
                               "rows": int(np.atleast_2d(X).shape[0]),
                               "predictions": np.asarray(y).tolist()})
        except Overloaded as e:
            # admission refusals: 429 back-pressure, except an unhealthy
            # process, which is a 503 service condition
            code = 503 if e.reason == "unhealthy" else 429
            return _done(code, {"error": "overloaded", "reason": e.reason,
                                "tenant": e.tenant})
        except DeadlineExceeded as e:
            return _done(504, {"error": "deadline_exceeded",
                               "tenant": e.tenant,
                               "deadline_ms": e.deadline_ms})
        except TimeoutError as e:
            return _done(504, {"error": "timeout", "detail": str(e)})
        except LightGBMError as e:
            return _done(503, {"error": "unavailable", "detail": str(e)})
        except (TypeError, ValueError, KeyError) as e:
            return _done(400, {"error": "bad_request", "detail": str(e)})


# -- audit hook (analysis/contracts.py predict_coalesced_bucket) --------
def audit_dispatch_fn(k: int = 1):
    """The jitted callable one coalesced raw batch dispatches — resolved
    through the SAME selector the dispatch path uses
    (``GBDT._coalesced_raw_fn``), so the jaxpr-audit contract traces the
    serving loop's real executable family and a runtime that grew its own
    entry would change what gets audited."""
    from ..models.gbdt import GBDT
    return GBDT._coalesced_raw_fn(k)
