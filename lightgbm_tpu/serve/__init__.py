"""In-process async serving runtime (README "Serving").

Continuous micro-batching of concurrent predicts onto one warm
executable: a request queue + coalescer packs concurrent requests into
the smallest covering pow-2 bucket rung (responses bitwise equal to
individual ``Booster.predict`` calls), pinned double-buffered host
staging feeds the device one batch ahead, and p99-SLO / queue-bound load
shedding turns overload into a typed :class:`Overloaded` error instead
of a hang.  Multi-model multi-tenant: N packed ensembles resident behind
one bucket ladder, hot-swappable without cooling the cache.
"""

from .runtime import MAX_BATCH_ROWS, Overloaded, ServingRuntime

__all__ = ["ServingRuntime", "Overloaded", "MAX_BATCH_ROWS"]
