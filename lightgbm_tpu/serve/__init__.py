"""In-process async serving runtime (README "Serving").

Continuous micro-batching of concurrent predicts onto one warm
executable: a request queue + coalescer packs concurrent requests into
the smallest covering pow-2 bucket rung (responses bitwise equal to
individual ``Booster.predict`` calls), pinned double-buffered host
staging feeds the device one batch ahead, and p99-SLO / queue-bound load
shedding turns overload into a typed :class:`Overloaded` error instead
of a hang.  Multi-model multi-tenant: N packed ensembles resident behind
one bucket ladder, hot-swappable without cooling the cache.

:class:`ServingFleet` replicates the dispatch side behind the same
admission queue: health-aware routing with an ejection/readmission
circuit breaker, ``serve_deadline_ms`` deadlines (typed
:class:`DeadlineExceeded`), exactly-once retry with a token budget,
optional p99-derived hedging, and a per-replica restart watchdog — the
resilient front door the chaos drills in tests/test_serve_fleet.py
exercise.
"""

from .fleet import ServingFleet
from .runtime import (MAX_BATCH_ROWS, DeadlineExceeded, Overloaded,
                      ServingRuntime)

__all__ = ["ServingRuntime", "ServingFleet", "Overloaded",
           "DeadlineExceeded", "MAX_BATCH_ROWS"]
