"""The resilient serving fleet: health-routed replicas behind one
admission queue (round 22; docs/ROBUSTNESS.md "Serving fleet
resilience").

:class:`ServingRuntime` is one dispatcher on one device — a single
wedged dispatch takes the whole front door with it.  This module
replicates the dispatch side N ways (one per device on a real slice; N
threads off-chip) while keeping EVERYTHING the solo runtime already
pinned: one admission queue, the same coalescer, the same pinned
staging discipline, the same ``GBDT.predict_coalesced`` entry (1
dispatch + 1 accounted sync per coalesced batch per replica, zero
retraces), and bitwise-identical responses.  What it adds is the
robustness layer the training side got rounds ago:

* **Health-aware routing** — each staged batch routes to the best
  replica by (queue depth, warm batch latency from the per-replica
  ``serve_replica_batch_ms`` reservoirs); a replica accumulating
  consecutive failures trips an ejection/readmission circuit breaker
  (``serve_replica_ejections_total``): ejected replicas sit out a
  jittered exponential cooldown, then readmit through a single
  half-open probe batch.  The LAST healthy replica is never ejected —
  the fleet degrades to single-replica + shedding, never to zero.
* **Deadline / retry / hedge discipline** — every admitted request can
  carry a ``serve_deadline_ms`` deadline (typed
  :class:`~lightgbm_tpu.serve.runtime.DeadlineExceeded`, distinct from
  :class:`~lightgbm_tpu.serve.runtime.Overloaded`); a failed, dead or
  hung replica dispatch requeues the batch's requests EXACTLY once onto
  a healthy replica (idempotent because predict is pure — and pinned by
  test so a future stateful path cannot silently double-dispatch),
  gated by a retry-token budget so a sick fleet degrades to shedding
  instead of retry-storming itself; optionally a batch in flight past a
  p99-derived delay is hedged onto a second replica, first completion
  wins.
* **Replica lifecycle** — the launcher watchdog's machinery per
  replica: heartbeat gauges (``serve_replica_heartbeat_ts{replica=}``),
  hang detection by heartbeat staleness (not exit codes — a thread
  wedged inside a dispatch never exits), restart with jittered
  exponential backoff, and a replacement that warms every served pack
  BEFORE joining rotation.  In-flight requests of a dead/hung replica
  requeue through the same exactly-once path.
* **Chaos surface** — the ``replica_dispatch`` / ``replica_death`` /
  ``replica_hang`` / ``swap_publish`` fault sites (utils/faults.py,
  call-counted; each batch touches the sites at two pipeline stages, so
  even/odd rounds select stage A "on receipt" vs stage B "dispatch
  retired, results unpublished") drive the tier-1 chaos drills in
  tests/test_serve_fleet.py: kill or hang a replica mid-open-loop and
  every admitted request still resolves with the solo runtime's exact
  bits.

Off-chip replica threads share the process-global executable cache, so
a replacement is warm by construction; the explicit pack-touch before
rotation is what keeps the discipline honest for per-device replicas on
real hardware (each device re-stages its pack).  Like runtime.py, this
module owns NO jitted code (tests/test_serve.py's AST pin covers the
whole serve/ directory).
"""

from __future__ import annotations

import random
import threading
import time
from queue import Empty, Queue
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs import metrics as _obs
from ..obs import server as _obs_server
from ..obs import trace as _trace
from ..utils import faults as _flt
from .runtime import DeadlineExceeded, Overloaded, ServingRuntime, _Request

# replica states (the serve_replica_state{replica=} gauge exports the int)
_ACTIVE, _HALF_OPEN, _EJECTED, _DEAD = 0, 1, 2, 3
_STATE_NAMES = {_ACTIVE: "active", _HALF_OPEN: "half_open",
                _EJECTED: "ejected", _DEAD: "dead"}
# routing reads a replica's warm p50 from its labeled reservoir at most
# this often (percentile() sorts the reservoir — cheap, not free)
_LAT_REFRESH_S = 0.05
# supervisor cadence: hang sweep, breaker cooldowns, restarts, hedging
_SUP_TICK_S = 0.01
# retry tokens: a fresh fleet can absorb a few failures before the
# per-admission refill (serve_retry_budget) has accumulated anything
_RETRY_TOKENS_INIT = 4.0
_RETRY_TOKENS_CAP = 64.0


class _ReplicaDeath(BaseException):
    """Raised inside a replica thread to model whole-replica death (the
    thread-fleet analogue of the launcher's worker_death).  BaseException
    so the batch-failure handler cannot swallow it."""


def _member_ctxs(reqs) -> Optional[List]:
    """The sampled members' trace contexts — the link targets a
    fleet-side span (failed leg, requeue, hedge) carries so every
    affected request's trace slice adopts it."""
    out = [r.ctx for r in reqs if r.ctx is not None and r.ctx.sampled]
    return out or None


class _Inflight:
    """What a replica is currently executing — enough for the supervisor
    to requeue it (hang/death) or hedge it (tail latency).  ``leg_ctx``
    is the dispatch leg's trace identity: the supervisor's hedge/death
    spans link to it so the chaos matrix reconstructs from the export."""

    __slots__ = ("batch", "skey", "t_mono", "hedged", "leg_ctx", "t_perf")

    def __init__(self, batch: List[_Request], skey,
                 leg_ctx=None, t_perf: float = 0.0):
        self.batch = batch
        self.skey = skey  # staging-pool key, None for serial items
        self.t_mono = time.monotonic()
        self.hedged = False
        self.leg_ctx = leg_ctx
        self.t_perf = t_perf


class _Replica:
    __slots__ = ("idx", "hand", "thread", "state", "fail_streak", "trips",
                 "cooldown_until", "probe_inflight", "inflight", "last_tick",
                 "restarts", "next_restart_at", "hung", "exhausted",
                 "lat_cache")

    def __init__(self, idx: int):
        self.idx = idx
        # the hand queue is STABLE across restarts: an item put while the
        # previous incarnation was dying is consumed by the replacement —
        # no request is ever stranded in a dead queue
        self.hand: Queue = Queue(maxsize=1)
        self.thread: Optional[threading.Thread] = None
        self.state = _ACTIVE
        self.fail_streak = 0
        self.trips = 0
        self.cooldown_until = 0.0
        self.probe_inflight = False
        self.inflight: Optional[_Inflight] = None
        self.last_tick = 0.0
        self.restarts = 0
        self.next_restart_at = 0.0
        self.hung = False
        self.exhausted = False
        self.lat_cache = (0.0, 0.0)  # (refreshed_at, p50_ms)

    def depth(self) -> int:
        """Approximate outstanding work (the routing load signal).  Not
        Queue.unfinished_tasks: a hung incarnation never task_done()s its
        item, which would bias the count forever."""
        return self.hand.qsize() + (1 if self.inflight is not None else 0)


class ServingFleet(ServingRuntime):
    """N health-routed replicas behind the inherited admission queue.

    >>> fl = ServingFleet(booster, replicas=2, deadline_ms=50.0)
    >>> with fl:
    ...     y = fl.predict(X)          # same bits as Booster.predict
    >>> # /predict, /healthz (replica table) ride the obs endpoint

    Knob defaults come from the first model's Config
    (``serve_replicas``, ``serve_deadline_ms``, ``serve_hedge_ms``,
    ``serve_retry_budget``, ``serve_replica_trip``,
    ``serve_replica_cooldown_ms``, ``serve_hang_timeout_ms``,
    ``serve_restart_backoff_ms``, ``serve_max_restarts``); explicit
    kwargs win, like the base runtime's.
    """

    def __init__(self, model=None, *, models: Optional[Dict[str, Any]] = None,
                 replicas: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 hedge_ms: Optional[float] = None,
                 retry_budget: Optional[float] = None,
                 trip: Optional[int] = None,
                 cooldown_ms: Optional[float] = None,
                 hang_timeout_ms: Optional[float] = None,
                 restart_backoff_ms: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 slo_p99_ms: Optional[float] = None,
                 tenant_quota: Optional[int] = None,
                 shed_unhealthy: bool = True,
                 start: bool = True):
        super().__init__(model, models=models, max_wait_ms=max_wait_ms,
                         max_queue=max_queue, slo_p99_ms=slo_p99_ms,
                         tenant_quota=tenant_quota,
                         shed_unhealthy=shed_unhealthy, start=False)
        cfg = next(iter(self._table.values())).cfg

        def _k(explicit, name, cast):
            return cast(getattr(cfg, name) if explicit is None else explicit)

        self._n_replicas = max(1, _k(replicas, "serve_replicas", int))
        self._deadline_s = _k(deadline_ms, "serve_deadline_ms", float) / 1e3
        self._hedge_ms = _k(hedge_ms, "serve_hedge_ms", float)
        self._retry_rate = _k(retry_budget, "serve_retry_budget", float)
        self._trip = max(1, _k(trip, "serve_replica_trip", int))
        self._cooldown_s = _k(cooldown_ms,
                              "serve_replica_cooldown_ms", float) / 1e3
        self._hang_s = _k(hang_timeout_ms, "serve_hang_timeout_ms",
                          float) / 1e3
        self._restart_backoff_s = _k(restart_backoff_ms,
                                     "serve_restart_backoff_ms", float) / 1e3
        self._max_restarts = max(0, _k(max_restarts, "serve_max_restarts",
                                       int))
        self._retry_tokens = _RETRY_TOKENS_INIT
        self._coal_done = False
        self._sup: Optional[threading.Thread] = None
        self._replicas = [_Replica(i) for i in range(self._n_replicas)]
        if start:
            self.start()

    # -- lifecycle -------------------------------------------------------
    def _spawn_workers(self) -> None:
        # warm every served pack once before ANY replica joins rotation —
        # the same "resident before the first request" discipline
        # add_model/swap_model already follow
        for g in list(self._table.values()):
            g._packed(0, -1)
        now = time.monotonic()
        for rep in self._replicas:
            rep.last_tick = now
            self._launch_replica_thread(rep)
        self._sup = threading.Thread(  # jaxlint: disable=L5 (joined via the _worker_threads() loop in stop())
            target=self._supervise_loop, daemon=True,
            name="lgbmtpu-fleet-supervisor")
        self._sup.start()
        self._coalescer = threading.Thread(  # jaxlint: disable=L5 (joined via the _worker_threads() loop in stop())
            target=self._coalesce_loop, daemon=True,
            name="lgbmtpu-fleet-coalescer")
        self._coalescer.start()
        _obs_server.set_health_extra(self._health_extra)
        with self._cv:
            self._publish_fleet_gauges()

    def _launch_replica_thread(self, rep: _Replica) -> None:
        rep.thread = threading.Thread(  # jaxlint: disable=L5 (non-hung replica threads are joined via the _worker_threads() loop in stop(); a HUNG replica is deliberately abandoned as a daemon — joining a wedged dispatch would hang shutdown)
            target=self._replica_loop, args=(rep,), daemon=True,
            name=f"lgbmtpu-replica-{rep.idx}")
        rep.thread.start()

    def _worker_threads(self) -> List[threading.Thread]:
        # join order matters: the coalescer first (it finishes routing the
        # drained queue), then the replicas (they finish their hands), then
        # the supervisor.  A HUNG replica's thread is excluded — it sleeps
        # inside a dispatch and would eat the whole join timeout; the
        # stop() drain sweep types-out whatever it held.
        out = [t for t in (self._coalescer,) if t is not None]
        out += [rep.thread for rep in self._replicas
                if rep.thread is not None and not rep.hung]
        if self._sup is not None:
            out.append(self._sup)
        return out

    def _shutdown_pipeline(self) -> None:
        # replicas poll this instead of a depth-1 sentinel: they must not
        # exit before the coalescer has routed the last drained batch
        self._coal_done = True

    def stop(self) -> None:
        if self._closed:
            super().stop()
            return
        super().stop()
        _obs_server.clear_health_extra(self._health_extra)
        with self._cv:
            _obs.gauge("serve_fleet_degraded").set(0.0)

    # -- admission (inherited) + retry-budget refill ---------------------
    def submit(self, X, *, model: str = "default",
               raw_score: bool = False, trace_ctx=None) -> _Request:
        req = super().submit(X, model=model, raw_score=raw_score,
                             trace_ctx=trace_ctx)
        if self._retry_rate > 0:
            with self._cv:
                self._retry_tokens = min(_RETRY_TOKENS_CAP,
                                         self._retry_tokens
                                         + self._retry_rate)
        return req

    def _take_retry_token_locked(self) -> bool:
        if self._retry_rate < 0:
            return True  # unlimited
        if self._retry_tokens >= 1.0:
            self._retry_tokens -= 1.0
            return True
        _obs.counter("serve_retry_budget_exhausted_total").inc()
        return False

    # -- routing ---------------------------------------------------------
    def _pipeline_idle(self) -> bool:
        for rep in self._replicas:
            if (rep.state == _ACTIVE and rep.inflight is None
                    and rep.hand.empty()):
                return True
        return False

    def _staging_pairs(self) -> int:
        # N replicas can each hold one batch in flight while the coalescer
        # stages the next — 2 pairs (the solo double buffer) would starve
        return self._n_replicas + 1

    def _lat_ms_locked(self, rep: _Replica, now: float) -> float:
        t, v = rep.lat_cache
        if now - t > _LAT_REFRESH_S:
            p = _obs.histogram(_obs.labeled(
                "serve_replica_batch_ms", replica=rep.idx)).percentile(50)
            v = 0.0 if p is None else float(p)
            rep.lat_cache = (now, v)
        return v

    def _route(self, avoid: int = -1) -> Optional[_Replica]:
        """Pick the healthiest replica for one staged batch: active (or
        half-open with a free probe slot), away from ``avoid`` (the
        replica a retried batch just failed on) when any alternative
        exists, minimizing (outstanding depth, warm p50).  Blocks while
        no replica is routable (all ejected/dead mid-restart); returns
        None only when the fleet is stopping or every replica slot is
        dead with its restarts exhausted."""
        with self._cv:
            while True:
                cands = [rep for rep in self._replicas
                         if rep.state == _ACTIVE
                         or (rep.state == _HALF_OPEN
                             and not rep.probe_inflight)]
                if avoid >= 0 and len(cands) > 1:
                    cands = [c for c in cands if c.idx != avoid] or cands
                if cands:
                    # a half-open replica with a free probe slot takes the
                    # next batch unconditionally: probes must actually run
                    # for readmission to ever happen, and min-latency
                    # routing would starve them (the freshly cooled replica
                    # rarely wins a (depth, p50) tiebreak)
                    half = [c for c in cands if c.state == _HALF_OPEN]
                    if half:
                        rep = min(half, key=lambda c: c.idx)
                        rep.probe_inflight = True
                        return rep
                    now = time.monotonic()
                    rep = min(cands, key=lambda c: (
                        c.depth(), self._lat_ms_locked(c, now), c.idx))
                    return rep
                if not self._running and self._closed:
                    return None
                if all(rep.state == _DEAD and rep.exhausted
                       for rep in self._replicas):
                    return None
                self._cv.wait(0.05)

    def _expire_deadlines(self, batch: List[_Request]) -> None:
        """Drop (typed-fail) requests already past their deadline BEFORE
        they spend staging + a dispatch."""
        if self._deadline_s <= 0:
            return
        now = time.monotonic()
        expired = [r for r in batch
                   if r.deadline is not None and now > r.deadline
                   and not r.event.is_set()]
        if not expired:
            return
        gone = set(id(r) for r in expired)
        batch[:] = [r for r in batch if id(r) not in gone]
        with self._cv:
            for r in expired:
                self._pending.discard(r)
        t = time.perf_counter()
        for r in expired:
            self._count_deadline(r.model)
            r.error = DeadlineExceeded(r.model, self._deadline_s * 1e3)
            r.t_done = t
            if r.ctx is not None and r.ctx.sampled:
                _trace.record_span(
                    "serve.request", t - r.t0, ctx=r.ctx, model=r.model,
                    rows=r.n, outcome="deadline", attempt=r.retries)
            r.event.set()

    def _stage_and_hand(self, g, batch: List[_Request]) -> None:
        self._expire_deadlines(batch)
        if not batch:
            return
        rep = self._route(max(r.avoid for r in batch))
        if rep is None:
            # stopping, or every replica slot is dead beyond restarts:
            # shed typed instead of queueing into nowhere (the coalescer's
            # error path fails the batch with this)
            raise Overloaded("unhealthy", batch[0].model)
        if batch[0].serial:
            rep.hand.put(("serial", batch, g))
            return
        rep.hand.put(self._stage_batch(g, batch))

    # -- replica worker --------------------------------------------------
    def _replica_loop(self, rep: _Replica) -> None:
        _obs.event("serve_replica_start", replica=rep.idx,
                   restarts=rep.restarts)
        try:
            while True:
                try:
                    item = rep.hand.get(timeout=0.05)
                except Empty:
                    with self._cv:
                        rep.last_tick = time.monotonic()
                    _obs.gauge(_obs.labeled(
                        "serve_replica_heartbeat_ts",
                        replica=rep.idx)).set(time.time())
                    if self._coal_done and not self._running:
                        break
                    continue
                self._replica_execute(rep, item)
        except _ReplicaDeath:
            self._on_replica_exit(rep, why="death")
        except BaseException as e:  # noqa: BLE001 — an escaping error IS
            # a replica death: the slot restarts, the batch requeues
            _obs.event("serve_replica_error", replica=rep.idx,
                       error=repr(e))
            self._on_replica_exit(rep, why="error")

    def _chaos(self, rep: _Replica) -> None:
        """The serve-side fault sites, touched once per pipeline stage
        (docs/ROBUSTNESS.md).  Order: death, hang, dispatch-failure."""
        if _flt.fire("replica_death"):
            raise _ReplicaDeath(f"replica {rep.idx}")
        _flt.maybe_hang("replica_hang")
        _flt.maybe_fail("replica_dispatch")

    def _replica_execute(self, rep: _Replica, item) -> None:
        kind, batch, payload = item
        staging = None
        total = sum(r.n for r in batch)
        nb = total
        if kind == "batch":
            g, x_dev, active, total, nb, skey, pair = payload
            staging = (skey, pair)
        t_batch = time.perf_counter()
        # the leg's trace identity: minted on receipt, stored on the
        # inflight record so the SUPERVISOR thread (hedge sweep, hang
        # detection) can link its spans to this exact dispatch attempt —
        # explicit context, never this thread's (empty) ambient stack
        leg_ctx = self._batch_ctx(batch)
        with self._cv:
            rep.inflight = _Inflight(batch, staging[0] if staging else None,
                                     leg_ctx=leg_ctx, t_perf=t_batch)
            rep.last_tick = time.monotonic()
        _obs.gauge(_obs.labeled("serve_replica_heartbeat_ts",
                                replica=rep.idx)).set(time.time())
        err: Optional[BaseException] = None
        outs: Optional[List[np.ndarray]] = None
        t_sync: Optional[float] = None
        try:
            try:
                self._chaos(rep)  # stage A: batch received, not dispatched
                if kind == "serial":
                    (r,) = batch
                    gg = payload if payload is not None \
                        else self._table[r.model]
                    outs = [gg.predict(r.x, raw_score=r.raw)]
                else:
                    convert = ((not batch[0].raw)
                               and g.objective is not None)
                    res = g.predict_coalesced(x_dev, active, total,
                                              convert=convert,
                                              trace_ctx=leg_ctx)
                    outs = []
                    off = 0
                    for r in batch:
                        outs.append(res[off:off + r.n])
                        off += r.n
                t_sync = time.perf_counter()  # accounted sync retired
                self._chaos(rep)  # stage B: dispatch retired, unpublished
            except _ReplicaDeath:
                raise
            except BaseException as e:  # noqa: BLE001 — a failed batch
                err = e  # fails (or requeues) its requests, not the thread
        finally:
            # the batch's accounted sync has retired (or it never ran):
            # the pinned pair may be reused.  This also runs on the way
            # OUT of a replica death — the dying thread returns its pair
            # cleanly, so only a HANG leaks one (the supervisor
            # compensates the pool).
            if staging is not None:
                self._return_staging(*staging)
        if err is None:
            self._publish_success(rep, batch, outs, total, nb,
                                  kind == "batch", t_batch, t_sync, leg_ctx)
        else:
            self._publish_failure(rep, batch, err, t_batch, leg_ctx)
        rep.hand.task_done()
        with self._cv:
            rep.inflight = None
            rep.last_tick = time.monotonic()
            self._cv.notify_all()

    def _publish_success(self, rep: _Replica, batch, outs, total, nb,
                         coalesced, t_batch, t_sync=None,
                         leg_ctx=None) -> None:
        now = time.perf_counter()
        attempt = max((r.retries for r in batch), default=0)
        for r, y in zip(batch, outs):
            if r.event.is_set():
                continue  # a hedged/raced twin already delivered — the
                # bits are identical either way (predict is pure)
            r.result = y
            # shared completion path (runtime.py): latency + phase
            # reservoirs, exemplar, and the serve.request span linked to
            # THIS leg — the one that actually delivered the bits
            self._finish_request(r, now, t_sync, leg_ctx,
                                 outcome="ok", replica=rep.idx)
        dt_batch_ms = (now - t_batch) * 1e3
        _obs.histogram("serve_replica_batch_ms").observe(dt_batch_ms)
        _obs.histogram(_obs.labeled(
            "serve_replica_batch_ms", replica=rep.idx)).observe(dt_batch_ms)
        if coalesced:
            _obs.counter("serve_batches_total").inc()
            _obs.counter("serve_coalesced_rows_total").inc(total)
            _obs.histogram("serve_batch_occupancy").observe(total / nb)
        if leg_ctx is not None:  # None = no member sampled: batch span
            _trace.record_span(  # obeys the admission decision too
                "serve.batch", now - t_batch, ctx=leg_ctx,
                requests=len(batch), rows=total,
                model=batch[0].model, coalesced=coalesced,
                replica=rep.idx, attempt=attempt, outcome="ok")
        with self._cv:
            for r in batch:
                self._pending.discard(r)
            rep.fail_streak = 0
            if rep.state == _HALF_OPEN:
                # probe succeeded: readmit
                rep.state = _ACTIVE
                rep.probe_inflight = False
                rep.trips = 0
                _obs.counter("serve_replica_readmissions_total").inc()
                _obs.counter(_obs.labeled(
                    "serve_replica_readmissions_total",
                    replica=rep.idx)).inc()
                _obs.event("serve_replica_readmit", replica=rep.idx)
                self._publish_fleet_gauges()

    def _publish_failure(self, rep: _Replica, batch,
                         err: BaseException, t_batch: float = 0.0,
                         leg_ctx=None) -> None:
        _obs.counter("serve_replica_failures_total").inc()
        _obs.counter(_obs.labeled("serve_replica_failures_total",
                                  replica=rep.idx)).inc()
        # the FAILED leg's span: its own identity (leg_ctx) plus links to
        # every member request, so a request's trace slice adopts this
        # leg even though the request span will link only to the leg
        # that eventually delivered — death/hang × stage reconstructs
        # from the export alone
        now = time.perf_counter()
        if leg_ctx is not None:  # None = no member sampled (admission)
            _trace.record_span(
                "serve.leg", now - (t_batch or now), ctx=leg_ctx,
                links=_member_ctxs(batch),
                replica=rep.idx, requests=len(batch),
                attempt=max((r.retries for r in batch), default=0),
                outcome="error", error=type(err).__name__,
                model=batch[0].model)
        with self._cv:
            rep.fail_streak += 1
            self._breaker_failure_locked(rep, time.monotonic())
            self._retry_or_fail_locked(rep, batch, err)

    # -- exactly-once requeue --------------------------------------------
    def _retry_or_fail_locked(self, rep: _Replica, reqs,
                              err: BaseException) -> int:
        """Under self._cv.  Requeue each unresolved request EXACTLY once
        (budget permitting) at the FRONT of the admission queue, marked
        to route away from ``rep``; requests already retried (or past
        budget) fail with ``err``.  Returns the requeue count."""
        live = [r for r in reqs if not r.event.is_set()]
        fresh = [r for r in live if r.retries == 0]
        fail = [r for r in live if r.retries != 0]  # already retried once
        # ONE token per failed BATCH (not per request): the budget bounds
        # how many redispatches a sick fleet performs, and a redispatch
        # costs one dispatch regardless of how many requests coalesced
        requeue: List[_Request] = []
        if fresh and self._take_retry_token_locked():
            requeue = fresh
            for r in requeue:
                r.retries = 1
                r.avoid = rep.idx
        else:
            fail.extend(fresh)
        for r in requeue:
            self._queued_per_tenant[r.model] = (
                self._queued_per_tenant.get(r.model, 0) + 1)
        self._queue[0:0] = requeue
        if requeue:
            _obs.gauge("serve_queue_depth").set(len(self._queue))
            _obs.counter("serve_requeues_total").inc(len(requeue))
            _obs.event("serve_requeue", replica=rep.idx,
                       requests=len(requeue), error=type(err).__name__)
            # the requeue decision as a span: links to every re-queued
            # request, so "this request was redispatched off replica K
            # after error E" reads straight out of the trace export
            # (skipped when no member was sampled — admission decision)
            rq_ctx = self._batch_ctx(requeue)
            if rq_ctx is not None:
                _trace.record_span(
                    "serve.requeue", 0.0, ctx=rq_ctx,
                    links=_member_ctxs(requeue), replica=rep.idx,
                    requests=len(requeue), error=type(err).__name__,
                    outcome="requeued", attempt=1)
        t = time.perf_counter()
        for r in fail:
            self._pending.discard(r)
            r.error = err
            r.t_done = t
            # terminal failure closes the request's span too — every
            # admitted sampled request leaves exactly one serve.request
            # span in the recorder, whatever its fate
            if r.ctx is not None and r.ctx.sampled:
                _trace.record_span(
                    "serve.request", t - r.t0, ctx=r.ctx,
                    model=r.model, rows=r.n, outcome="failed",
                    error=type(err).__name__, attempt=r.retries,
                    replica=rep.idx)
            r.event.set()
        self._cv.notify_all()
        return len(requeue)

    # -- circuit breaker -------------------------------------------------
    def _active_count_locked(self) -> int:
        return sum(1 for rep in self._replicas if rep.state == _ACTIVE)

    def _breaker_failure_locked(self, rep: _Replica, now: float) -> None:
        if rep.state == _HALF_OPEN:
            # the probe itself failed: straight back out, longer cooldown
            rep.probe_inflight = False
            self._eject_locked(rep, now)
        elif rep.state == _ACTIVE and rep.fail_streak >= self._trip:
            if self._active_count_locked() > 1:
                self._eject_locked(rep, now)
            # else: the LAST healthy replica is never ejected — the fleet
            # degrades to single-replica + shedding, never to zero

    def _eject_locked(self, rep: _Replica, now: float) -> None:
        rep.state = _EJECTED
        rep.trips += 1
        back = self._cooldown_s * (2 ** (rep.trips - 1))
        rep.cooldown_until = now + back * (0.5 + random.random())
        rep.fail_streak = 0
        _obs.counter("serve_replica_ejections_total").inc()
        _obs.counter(_obs.labeled("serve_replica_ejections_total",
                                  replica=rep.idx)).inc()
        _obs.event("serve_replica_eject", replica=rep.idx, trips=rep.trips,
                   cooldown_ms=round((rep.cooldown_until - now) * 1e3, 2))
        self._publish_fleet_gauges()

    # -- death / hang lifecycle ------------------------------------------
    def _on_replica_exit(self, rep: _Replica, why: str) -> None:
        """Runs in the DYING replica thread: mark the slot dead, requeue
        whatever it held (its staging pair was already returned on the
        way out), and schedule the replacement."""
        with self._cv:
            self._mark_dead_locked(rep, hung=False, why=why)

    def _mark_dead_locked(self, rep: _Replica, hung: bool,
                          why: str) -> None:
        now = time.monotonic()
        rep.state = _DEAD
        rep.hung = hung
        rep.probe_inflight = False
        name = ("serve_replica_hangs_total" if hung
                else "serve_replica_deaths_total")
        _obs.counter(name).inc()
        _obs.counter(_obs.labeled(name, replica=rep.idx)).inc()
        _obs.event("serve_replica_hang" if hung else "serve_replica_death",
                   replica=rep.idx, why=why, restarts=rep.restarts)
        infl, rep.inflight = rep.inflight, None
        err = RuntimeError(
            f"replica {rep.idx} {'hung' if hung else 'died'} ({why})")
        if infl is not None and infl.leg_ctx is not None:
            # the leg that died/hung with work in flight: the span wears
            # the leg's own stored context (minted by the replica thread
            # on receipt — the supervisor/dying thread must NOT invent a
            # fresh one) and links every stranded request; a None leg
            # context means no member was sampled, so the span drops too
            _trace.record_span(
                "serve.leg", time.perf_counter() - (infl.t_perf or 0.0)
                if infl.t_perf else 0.0,
                ctx=infl.leg_ctx, links=_member_ctxs(infl.batch),
                replica=rep.idx, requests=len(infl.batch),
                attempt=max((r.retries for r in infl.batch), default=0),
                outcome="hang" if hung else "death", error=why)
        if infl is not None:
            if hung and infl.skey is not None:
                # the wedged thread still owns its pinned pair: grow the
                # rung's pool by one fresh pair so the coalescer cannot
                # starve (if the thread ever wakes, its late return only
                # makes the pool one pair deeper — never corrupts, the
                # pair is out of every in-flight batch by then)
                nb, f = infl.skey
                self._staging[infl.skey].put(
                    (np.zeros((nb, f), np.float32), np.zeros(nb, bool)))
            self._retry_or_fail_locked(rep, infl.batch, err)
        if rep.restarts >= self._max_restarts:
            rep.exhausted = True
            # no replacement will ever drain this hand: requeue/fail its
            # queued items now instead of stranding them
            self._drain_hand_locked(rep, err)
            _obs.event("serve_replica_abandoned", replica=rep.idx)
        else:
            back = self._restart_backoff_s * (2 ** rep.restarts)
            rep.next_restart_at = now + back * (0.5 + random.random())
        self._publish_fleet_gauges()
        self._cv.notify_all()

    def _drain_hand_locked(self, rep: _Replica, err: BaseException) -> None:
        while True:
            try:
                item = rep.hand.get_nowait()
            except Empty:
                return
            kind, batch, payload = item
            if kind == "batch":
                # never dispatched: the retry path re-stages from the
                # requests' own rows, so the pair goes straight back
                self._return_staging(payload[5], payload[6])
            rep.hand.task_done()
            self._retry_or_fail_locked(rep, batch, err)

    def _restart_replica(self, rep: _Replica) -> None:
        """Outside self._cv: warm FIRST, then join rotation — a cold
        replacement must never catch live traffic."""
        with self._cv:
            gs = list(self._table.values())
        for g in gs:
            g._packed(0, -1)
        with self._cv:
            rep.restarts += 1
            rep.state = _ACTIVE
            rep.hung = False
            rep.exhausted = False
            rep.fail_streak = 0
            rep.probe_inflight = False
            rep.inflight = None
            rep.last_tick = time.monotonic()
            _obs.counter("serve_replica_restarts_total").inc()
            _obs.counter(_obs.labeled("serve_replica_restarts_total",
                                      replica=rep.idx)).inc()
            _obs.event("serve_replica_restart", replica=rep.idx,
                       restarts=rep.restarts)
            self._publish_fleet_gauges()
            self._cv.notify_all()
        self._launch_replica_thread(rep)

    # -- supervisor ------------------------------------------------------
    def _supervise_loop(self) -> None:
        while self._running:
            now = time.monotonic()
            spawn: List[_Replica] = []
            with self._cv:
                for rep in self._replicas:
                    if rep.state == _EJECTED and now >= rep.cooldown_until:
                        rep.state = _HALF_OPEN
                        rep.probe_inflight = False
                        _obs.event("serve_replica_half_open",
                                   replica=rep.idx)
                        self._publish_fleet_gauges()
                        self._cv.notify_all()
                    if (rep.state in (_ACTIVE, _HALF_OPEN)
                            and rep.inflight is not None
                            and now - rep.last_tick > self._hang_s):
                        self._mark_dead_locked(rep, hung=True,
                                               why="heartbeat stale")
                    if (rep.state == _DEAD and not rep.exhausted
                            and now >= rep.next_restart_at
                            and (rep.hung or rep.thread is None
                                 or not rep.thread.is_alive())):
                        # claim the slot so one restart spawns exactly once
                        rep.next_restart_at = float("inf")
                        spawn.append(rep)
                if self._hedge_ms != 0:
                    self._hedge_sweep_locked(now)
            for rep in spawn:
                self._restart_replica(rep)
            time.sleep(_SUP_TICK_S)

    # -- hedging ---------------------------------------------------------
    def _hedge_delay_s(self) -> float:
        if self._hedge_ms > 0:
            return self._hedge_ms / 1e3
        # auto: p99-derived from the fleet-wide batch reservoir
        p = _obs.histogram("serve_replica_batch_ms").percentile(99)
        return (float(p) / 1e3) if p else 0.05

    def _hedge_sweep_locked(self, now: float) -> None:
        delay = self._hedge_delay_s()
        for rep in self._replicas:
            infl = rep.inflight
            if infl is None or infl.hedged or now - infl.t_mono <= delay:
                continue
            others = any(r.state == _ACTIVE and r is not rep
                         for r in self._replicas)
            if not others:
                continue
            infl.hedged = True
            twins = [r for r in infl.batch if not r.event.is_set()]
            if not twins:
                continue
            for r in twins:
                r.avoid = rep.idx
                self._queued_per_tenant[r.model] = (
                    self._queued_per_tenant.get(r.model, 0) + 1)
            self._queue[0:0] = twins
            _obs.counter("serve_hedges_total").inc()
            _obs.event("serve_hedge", replica=rep.idx, requests=len(twins),
                       delay_ms=round(delay * 1e3, 2))
            # the hedge pair as links: the slow original leg + every
            # hedged request — first result wins, and both legs stay
            # reachable from the request's trace slice
            hedge_links = list(_member_ctxs(twins) or [])
            if infl.leg_ctx is not None:
                hedge_links.append(infl.leg_ctx)
            hedge_ctx = self._batch_ctx(twins)
            if hedge_ctx is not None:  # None = no twin sampled
                _trace.record_span(
                    "serve.hedge", 0.0, ctx=hedge_ctx,
                    links=hedge_links or None, replica=rep.idx,
                    requests=len(twins), delay_ms=round(delay * 1e3, 2),
                    outcome="hedged")
            self._cv.notify_all()

    # -- observability ---------------------------------------------------
    def _publish_fleet_gauges(self) -> None:
        """Under self._cv: routing-state gauges + the /healthz-driving
        degraded flag (obs/server.py DEGRADED_GAUGES)."""
        degraded = any(rep.state != _ACTIVE for rep in self._replicas)
        _obs.gauge("serve_fleet_degraded").set(1.0 if degraded else 0.0)
        for rep in self._replicas:
            _obs.gauge(_obs.labeled("serve_replica_state",
                                    replica=rep.idx)).set(float(rep.state))

    def _health_extra(self) -> Dict[str, Any]:
        """The /healthz replica table (obs/server.py set_health_extra)."""
        with self._cv:
            return {
                "replicas": [
                    {"replica": rep.idx,
                     "state": _STATE_NAMES[rep.state],
                     "fail_streak": rep.fail_streak,
                     "restarts": rep.restarts,
                     "depth": rep.depth()}
                    for rep in self._replicas],
                "retry_tokens": round(self._retry_tokens, 2),
            }

    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        with self._cv:
            out["replicas"] = {rep.idx: _STATE_NAMES[rep.state]
                               for rep in self._replicas}
            out["retry_tokens"] = round(self._retry_tokens, 2)
        return out
