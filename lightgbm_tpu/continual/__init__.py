"""Continuous training: streaming refit + zero-downtime rollover
(README "Continuous training"; the train-while-serving loop beside
``lightgbm_tpu/serve``)."""

from .refit import ContinualError, make_refit_entry, refit_leaves
from .runtime import ContinualRunner

__all__ = ["ContinualRunner", "ContinualError", "refit_leaves",
           "make_refit_entry"]
