"""Continuous training: streaming refit + zero-downtime rollover
(README "Continuous training"; the train-while-serving loop beside
``lightgbm_tpu/serve``)."""

from .refit import (ContinualError, fleet_refit_leaves,
                    make_fleet_refit_entry, make_refit_entry, refit_leaves)
from .runtime import ContinualRunner

__all__ = ["ContinualRunner", "ContinualError", "refit_leaves",
           "make_refit_entry", "fleet_refit_leaves",
           "make_fleet_refit_entry"]
