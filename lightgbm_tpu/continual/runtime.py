"""Continuous training: streaming refit + zero-downtime rollover
(round 19; README "Continuous training", ROADMAP item closed).

Every primitive predates this module — ``BinCacheStream`` chunked ingest
with CRC'd append-able caches (io/stream.py), on-device ensemble
mutation (``refit``/``set_leaf_output``), bitwise raw-delta fleet
checkpoints (utils/checkpoint.py), and the version-keyed ``_packed``
hot-swap that keeps in-flight predicts warm (round 18).  This module is
the PROCESS composing them into the train-while-serving loop:

* **Streaming ingest** — :meth:`ContinualRunner.ingest` takes raw
  ``(X, y)`` chunks.  Each chunk is binned against the FROZEN mappers
  (out-of-range values clamp into the edge bins and are COUNTED —
  ``continual_clamped_values_total`` — never rebinned: rebinning would
  silently reshape every histogram the live trees were grown on),
  appended to the CRC-verified durable cache when one is configured
  (``io/stream.py::append_rows``), and accumulated into a rolling
  training window.
* **Periodic on-device update** — policy-driven
  (``update_every_rows=`` / ``update_every_s=``): the cheap path renews
  leaf values of the EXISTING structure on the fresh window in one
  donated dispatch (continual/refit.py, the ``continual_refit_leaves``
  jaxpr contract), escalating to APPENDING ``append_trees=`` boosted
  trees seeded ``init_model``-style from the live ensemble through the
  ordinary ``engine.train`` machinery — same growers, same budgets,
  bitwise-reproducible offline.
* **Zero-downtime rollover** — every update builds the candidate on a
  CLONE; the serving ensemble is never mutated in place.  The candidate
  is checkpointed (raw-delta snapshot + fleet manifest, world_size=1 —
  the SAME manifest machinery elastic recovery resumes from), then
  published through ``ServingRuntime.swap_model``, whose pack is built
  BEFORE publication: in-flight predicts keep the previous version's
  pack (the round-18 version-keyed cache) and never go cold.  A crash at
  the armed ``continual_swap`` fault site lands BETWEEN the checkpoint
  and the publish: the previous ensemble keeps serving, no torn pack is
  ever published, and a restarted runner resumes from the manifest.
* **Drift + staleness observability** — per-chunk label-drift and clamp
  counters ride the existing event stream (``continual_chunk``), the
  ``model_staleness_s`` / ``model_staleness_rows`` gauges report how far
  the serving ensemble lags ingest (seconds-behind + rows-behind), and
  ``staleness_slo_s=`` arms the ``continual_staleness_exceeded`` gauge
  that flips ``/healthz`` degraded through the round-18
  ``DEGRADED_GAUGES`` mechanism.

Like the serving runtime, this module owns NO jitted code of its own
(jaxlint R16 additionally pins that every ensemble mutation in
continual/serve code routes through ``_invalidate_pred_cache``): the
refit dispatch lives in continual/refit.py, appends run the audited
training entry, and predictions stay the serving loop's.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..basic import Booster, LightGBMError
from ..obs import metrics as _obs
from ..obs import trace as _trace
from ..utils import checkpoint as _checkpoint
from ..utils import locktrace as _lt
from ..utils import faults as _faults
from ..utils import sanitizer as _san
from .refit import ContinualError, make_refit_entry, refit_eligible, \
    refit_leaves

# the runner thread's wake cadence: staleness gauges refresh and the
# update policy is re-evaluated at this period (update_every_s is
# honored to within one tick)
_TICK_S = 0.05


class ContinualRunner:
    """In-process continual-training runtime beside (optionally) a live
    :class:`~lightgbm_tpu.serve.ServingRuntime`.

    >>> rt = lgb.serve(booster, {"serve_max_wait_ms": 2})
    >>> cr = lgb.continual_train(booster, {"update_every_rows": 4096,
    ...                                    "append_trees": 5},
    ...                          runtime=rt, reference=train_ds)
    >>> cr.ingest(X_new, y_new)   # serving keeps answering throughout
    >>> cr.stop(); rt.stop()

    ``reference`` (a constructed Dataset, typically the training set or a
    ``save_binary`` cache path) supplies the FROZEN bin mappers for
    ingest binning, the durable cache, and append training; without it
    the runner is refit-only with unbinned ingest.  ``state_dir`` arms
    durable rollover checkpoints + crash resume; ``cache_path`` arms the
    durable CRC'd ingest cache.  Policy knobs default from the model's
    Config (``update_every_rows`` / ``update_every_s`` /
    ``append_trees`` / ``drift_window``); explicit kwargs win.
    """

    def __init__(self, model, *, runtime=None, model_name: str = "default",
                 reference=None, state_dir: Optional[str] = None,
                 cache_path: Optional[str] = None,
                 update_every_rows: Optional[int] = None,
                 update_every_s: Optional[float] = None,
                 append_trees: Optional[int] = None,
                 drift_window: Optional[int] = None,
                 append_every_rows: Optional[int] = None,
                 window_rows: int = 65536,
                 staleness_slo_s: float = 0.0,
                 resume: bool = False,
                 snapshot_keep: int = 0,
                 start: bool = False):
        self._live: Booster = (model if isinstance(model, Booster)
                               else Booster(model_file=model))
        cfg = self._live._gbdt.cfg
        self._runtime = runtime
        self._model_name = model_name
        if runtime is not None and model_name not in runtime.models():
            raise LightGBMError(
                f"model {model_name!r} is not served by the runtime "
                f"(have {runtime.models()}) — the runner can only roll "
                "over a model the serving loop already publishes")
        self._state_dir = state_dir
        self._cache_path = cache_path
        self._update_every_rows = int(
            cfg.update_every_rows if update_every_rows is None
            else update_every_rows)
        self._update_every_s = float(
            cfg.update_every_s if update_every_s is None else update_every_s)
        self._append_trees = int(
            cfg.append_trees if append_trees is None else append_trees)
        self._drift_window = max(int(
            cfg.drift_window if drift_window is None else drift_window), 1)
        # escalation threshold: rows since the last append before an
        # auto update appends trees instead of refitting.  Defaults to 4
        # row-triggered update periods; for purely time-driven policies
        # (update_every_rows=0) it defaults to a full rolling window —
        # NOT a handful of rows, which would turn every timed update
        # into a tree append
        self._append_every_rows = int(
            append_every_rows if append_every_rows is not None
            else (4 * self._update_every_rows if self._update_every_rows > 0
                  else int(window_rows)))
        self._window_rows = int(window_rows)
        self._staleness_slo_s = float(staleness_slo_s)
        self._snapshot_keep = int(snapshot_keep)
        # durable-ingest append mode: >= 1 routes ingest appends into
        # CRC'd sidecar segments (O(new rows) per chunk) with threshold
        # compaction, instead of rewriting the whole cache every chunk
        self._seg_threshold = int(cfg.bin_cache_segment_threshold)

        # frozen mappers: an explicit reference Dataset (or save_binary
        # cache path) wins; else the booster's own training set
        self._ref_dataset = None
        binner = None
        if reference is not None:
            from ..basic import Dataset

            ref = (reference if isinstance(reference, Dataset)
                   else Dataset(reference, params={"verbosity": -1}))
            ref.construct()
            self._ref_dataset = ref
            binner = ref.binner
        elif getattr(self._live._gbdt, "train_set", None) is not None:
            self._ref_dataset = self._live._gbdt.train_set
            binner = self._ref_dataset.binner
        self._binner = binner
        if cache_path is not None and binner is None:
            raise ContinualError(
                "cache_path= needs the frozen bin mappers — pass "
                "reference= (the training Dataset or its save_binary "
                "cache)")

        # the refit entry is built ONCE for the runner's lifetime, so
        # every rollover reuses the compiled executable (continual/refit)
        self._refit_entry = None
        if refit_eligible(self._live._gbdt) is None:
            self._refit_entry = make_refit_entry(
                self._live._gbdt.objective, float(cfg.refit_decay_rate),
                float(cfg.lambda_l2),
                k=self._live._gbdt.num_tree_per_iteration)

        # rolling window (raw rows + labels, host): refit traverses raw
        # values, appends bin via the reference mappers — both read it
        self._wlock = _lt.lock("continual.window")
        self._wx: List[np.ndarray] = []
        self._wy: List[np.ndarray] = []
        self._wrows = 0
        self._pending_rows = 0
        self._rows_since_append = 0
        # (rows, ingest monotonic ts) per still-pending chunk, oldest
        # first: staleness reads the TRUE age of the oldest row an
        # update has not yet incorporated — rows ingested mid-update
        # keep their original timestamps when the update completes
        self._pending_ts: List[tuple] = []
        # rows consumed from the ledger by an IN-FLIGHT update: still
        # unpublished, so staleness keeps reporting them until the swap
        # actually lands (cleared at publication, folded back on failure)
        self._inflight_rows = 0
        self._inflight_oldest: Optional[float] = None
        self._label_hist: List[tuple] = []  # (rows, sum) per chunk
        self._mu = _lt.lock("continual.update")  # one update/rollover at a time
        # durable-cache appends are read-rewrite-replace: serialized
        # here so concurrent ingest() calls cannot drop each other's
        # rows (one process owns a cache; cross-process appends are out
        # of contract, like save_binary itself)
        self._cache_lock = _lt.lock("continual.cache")
        # runner-thread failure backoff: a deterministic update failure
        # must not retry at tick cadence forever
        self._fail_backoff_s = 0.0
        self._retry_after = 0.0
        self._seq = 0
        self._updates = 0
        self._running = False
        self._thread: Optional[threading.Thread] = None

        if resume:
            if state_dir is None:
                raise ContinualError("resume=True needs state_dir=")
            found = _checkpoint.latest_valid_fleet_manifest(state_dir, 1)
            if found is not None:
                seq, _path, manifest = found
                self._live = Booster(model_file=manifest["snapshot"])
                self._live._gbdt.cfg = cfg
                self._seq = seq
                _obs.counter("continual_resumes_total").inc()
                _obs.event("continual_resume", seq=seq,
                           snapshot=manifest["snapshot"])
                if runtime is not None:
                    runtime.swap_model(model_name, self._live)
        self._last_rollover = time.monotonic()
        self._publish_staleness()
        if start:
            self.start()

    # -- properties ------------------------------------------------------
    @property
    def booster(self) -> Booster:
        """The CURRENT ensemble (the one the serving runtime publishes)."""
        return self._live

    @property
    def seq(self) -> int:
        """Rollovers published so far (the fleet-checkpoint round)."""
        return self._seq

    def stats(self) -> Dict[str, Any]:
        with self._wlock:
            return {"window_rows": self._wrows,
                    "pending_rows": self._pending_rows,
                    "rows_since_append": self._rows_since_append,
                    "seq": self._seq, "updates": self._updates,
                    "running": self._running}

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ContinualRunner":
        if self._running:
            return self
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="lgbmtpu-continual")
        self._thread.start()
        _obs.event("continual_start",
                   update_every_rows=self._update_every_rows,
                   update_every_s=self._update_every_s,
                   append_trees=self._append_trees)
        return self

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        _obs.event("continual_stop", seq=self._seq)

    def __enter__(self) -> "ContinualRunner":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _loop(self) -> None:
        while self._running:
            time.sleep(_TICK_S)
            self._publish_staleness()
            if time.monotonic() < self._retry_after:
                continue  # backing off after a failed update
            try:
                if self._due():
                    self.update("auto")
                    self._fail_backoff_s = 0.0
            except Exception as e:  # noqa: BLE001 — the trainer thread
                # must never die silently beside a live serving loop: the
                # failure is counted, evented, /healthz-visible
                # (obs/server.py DEGRADED_COUNTERS), and retried with
                # exponential backoff — a deterministic failure must not
                # spin at tick cadence while the PREVIOUS ensemble keeps
                # serving
                self._fail_backoff_s = min(
                    max(self._fail_backoff_s * 2, 1.0), 30.0)
                self._retry_after = time.monotonic() + self._fail_backoff_s
                _obs.counter("continual_update_failures_total").inc()
                _obs.event("continual_update_failed", error=repr(e),
                           retry_in_s=self._fail_backoff_s)

    # -- ingest ----------------------------------------------------------
    def ingest(self, X, y) -> Dict[str, Any]:
        """Take one chunk of fresh rows.  Bins against the frozen
        mappers (clamp-and-count), appends to the durable cache when
        configured, grows the rolling window, refreshes staleness and
        drift telemetry.  Returns the chunk's summary (also the
        ``continual_chunk`` event payload)."""
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        y = np.asarray(y, np.float64).ravel()
        if X.shape[0] != len(y):
            raise ValueError(f"ingest: {X.shape[0]} rows but {len(y)} labels")
        if not np.isfinite(y).all():
            bad = int(np.nonzero(~np.isfinite(y))[0][0])
            raise LightGBMError(
                f"ingest: non-finite label at chunk row {bad} — a NaN/inf "
                "target would poison every later update (the same guard "
                "Dataset construction applies)")
        n = int(X.shape[0])
        clamped = 0
        bins = None
        if self._binner is not None:
            clamped = self._count_clamped(X)
            bins = self._binner.transform(X)
            if self._cache_path is not None:
                with self._cache_lock:
                    self._append_cache(bins, y)
        with self._wlock:
            self._wx.append(X)
            self._wy.append(y)
            self._wrows += n
            self._pending_rows += n
            self._rows_since_append += n
            self._pending_ts.append((n, time.monotonic()))
            # rolling window: drop whole oldest chunks past the cap (the
            # durable cache, when armed, keeps the full history).  The
            # pending-age ledger entries map 1:1 onto the window's
            # TRAILING chunks (each ingest appends one; updates consume
            # whole entries from the front), so an evicted chunk is
            # still-pending exactly when every window chunk is — those
            # rows will never reach an update: they leave the staleness
            # accounting and are COUNTED as lost instead of silently
            # reported as incorporated
            evicted_pending = 0
            while self._wrows > self._window_rows and len(self._wx) > 1:
                dropped = self._wx[0].shape[0]
                if len(self._pending_ts) == len(self._wx):
                    self._pending_ts.pop(0)
                    self._pending_rows = max(self._pending_rows - dropped, 0)
                    evicted_pending += dropped
                self._wrows -= dropped
                self._wx.pop(0)
                self._wy.pop(0)
            drift = self._note_drift(y)
        _obs.counter("continual_ingested_rows_total").inc(n)
        if clamped:
            _obs.counter("continual_clamped_values_total").inc(clamped)
        if evicted_pending:
            _obs.counter(
                "continual_window_evicted_pending_rows_total").inc(
                evicted_pending)
            _obs.event("continual_window_overflow", rows=evicted_pending)
        self._publish_staleness()
        summary = dict(rows=n, clamped=clamped, **drift)
        _obs.event("continual_chunk", **summary)
        return summary

    def _count_clamped(self, X: np.ndarray) -> int:
        """Out-of-range raw values per the FROZEN mappers: they clamp
        into the edge bins (numeric) or the fallback bin (unseen
        categories) — never a rebin — and the count is the cheapest
        honest covariate-shift signal there is."""
        total = 0
        for j, m in enumerate(self._binner.mappers):
            col = X[:, j]
            finite = np.isfinite(col)
            if m.is_categorical:
                if m.categories is not None and len(m.categories):
                    known = np.isin(col, np.asarray(m.categories, np.float64))
                    total += int(np.count_nonzero(finite & ~known))
            else:
                total += int(np.count_nonzero(
                    finite & ((col < m.min_value) | (col > m.max_value))))
        return total

    def _note_drift(self, y: np.ndarray) -> Dict[str, float]:
        """Under self._wlock: label-mean drift of this chunk vs the
        rolling drift_window baseline (the chunks BEFORE this one)."""
        base_rows = sum(r for r, _ in self._label_hist)
        base_sum = sum(s for _, s in self._label_hist)
        chunk_mean = float(y.mean()) if len(y) else 0.0
        drift = (abs(chunk_mean - base_sum / base_rows)
                 if base_rows else 0.0)
        self._label_hist.append((len(y), float(y.sum())))
        while (sum(r for r, _ in self._label_hist) - self._label_hist[0][0]
               >= self._drift_window and len(self._label_hist) > 1):
            self._label_hist.pop(0)
        _obs.gauge("continual_label_drift").set(drift)
        return {"label_mean": chunk_mean, "label_drift": drift}

    def _append_cache(self, bins: np.ndarray, y: np.ndarray) -> None:
        import os

        from ..io.stream import append_rows, create_bin_cache

        if not os.path.exists(self._cache_path):
            names = (self._ref_dataset.feature_names
                     if self._ref_dataset is not None else
                     [f"Column_{j}" for j in range(len(self._binner.mappers))])
            # atomic creation with shared-reader permissions — the one
            # crash-safety recipe, owned by io/stream.py for both the
            # create and append halves
            create_bin_cache(self._cache_path, bins, self._binner.mappers,
                             label=y, feature_names=names)
        else:
            append_rows(self._cache_path, bins, label=y,
                        segment_threshold=self._seg_threshold or None)

    # -- update policy ---------------------------------------------------
    def _due(self) -> bool:
        with self._wlock:
            pending = self._pending_rows
            oldest = self._pending_ts[0][1] if self._pending_ts else None
        if pending <= 0:
            return False
        if 0 < self._update_every_rows <= pending:
            return True
        return (self._update_every_s > 0 and oldest is not None
                and time.monotonic() - oldest >= self._update_every_s)

    def _choose_kind(self, mode: str) -> str:
        if mode in ("refit", "append"):
            return mode
        if self._refit_entry is None and self._append_trees > 0:
            # refit-ineligible ensemble (multiclass/linear/RF) with an
            # append path configured: auto updates take it instead of
            # failing toward the refit the envelope already refused
            return "append"
        if (self._append_trees > 0
                and self._rows_since_append >= self._append_every_rows):
            return "append"
        return "refit"

    # -- the rollover ----------------------------------------------------
    def update(self, mode: str = "auto") -> Optional[str]:
        """Run one policy-driven update + zero-downtime rollover.  Returns
        the kind performed ("refit"/"append") or None when the window is
        empty.  Serializable: one update at a time; ingest stays
        concurrent."""
        with self._mu:
            with self._wlock:
                if self._wrows == 0:
                    return None
                Xw = np.concatenate(self._wx, axis=0)
                yw = np.concatenate(self._wy)
                # consume the pending ledger AT SNAPSHOT TIME, under the
                # same lock as the snapshot: a mid-build ingest that
                # evicts window chunks then sees only the NEW rows'
                # entries, so a chunk the update IS training on can
                # never be double-accounted as "evicted pending" AND
                # subtracted again below (restored wholesale if the
                # build fails — those rows were not incorporated)
                consumed = self._pending_ts
                self._pending_ts = []
                trained_pending = self._pending_rows
                self._pending_rows = 0
                # the consumed rows stay visible to staleness as
                # IN-FLIGHT until the rollover publishes: the serving
                # model is still stale for them, and the SLO gauge must
                # not flip healthy for the duration of the build
                self._inflight_rows = trained_pending
                self._inflight_oldest = consumed[0][1] if consumed else None
            kind = self._choose_kind(mode)
            c0 = _san.compile_totals()
            # the rollover's trace identity (ISSUE-20 vocabulary): build,
            # checkpoint and swap legs all record under this one context,
            # so a rollover published mid-request-storm reads as ONE
            # connected story next to the serve.request spans in the
            # merged flight recorder
            roll_ctx = _trace.TraceContext(_trace.new_trace_id())
            t_roll = time.perf_counter()
            try:
                with _trace.span(f"continual_{kind}", parent=roll_ctx,
                                 rows=int(Xw.shape[0]),
                                 seq=self._seq + 1):
                    if kind == "append":
                        candidate = self._build_append(Xw, yw)
                    else:
                        candidate = self._build_refit(Xw, yw)
            except BaseException:
                lost = 0
                with self._wlock:
                    self._pending_ts = consumed + self._pending_ts
                    self._pending_rows += trained_pending
                    self._inflight_rows = 0
                    self._inflight_oldest = None
                    # chunks evicted by a mid-build ingest are gone from
                    # the window: reconcile the restored ledger against
                    # what a retry can actually still train (oldest
                    # pending rows beyond the window count as LOST, the
                    # same honesty rule the eviction path applies)
                    excess = self._pending_rows - self._wrows
                    while excess > 0 and self._pending_ts:
                        r, ts = self._pending_ts[0]
                        take = min(r, excess)
                        if take == r:
                            self._pending_ts.pop(0)
                        else:
                            self._pending_ts[0] = (r - take, ts)
                        self._pending_rows -= take
                        lost += take
                        excess -= take
                if lost:
                    _obs.counter(
                        "continual_window_evicted_pending_rows_total").inc(
                        lost)
                    _obs.event("continual_window_overflow", rows=lost)
                _trace.record_span("continual.rollover",
                                   time.perf_counter() - t_roll,
                                   ctx=roll_ctx, mode=kind,
                                   seq=self._seq + 1, outcome="error")
                raise
            c1 = _san.compile_totals()
            seq = self._seq + 1
            if self._state_dir is not None:
                # durable BEFORE visible: the raw-delta snapshot + fleet
                # manifest land first, so a crash in the swap window
                # below resumes the UPDATE while the old ensemble keeps
                # serving (no torn pack is ever published — swap_model
                # packs before it publishes)
                with _trace.span("checkpoint.snapshot", parent=roll_ctx,
                                 seq=seq):
                    _checkpoint.write_fleet_checkpoint(
                        self._state_dir,
                        candidate.model_to_string(raw_deltas=True), seq,
                        world_size=1, keep=self._snapshot_keep)
            # the continual_swap fault site (docs/ROBUSTNESS.md): a hard
            # crash between checkpoint and publication
            _faults.maybe_crash("continual_swap", seq)
            with _trace.span("continual.swap", parent=roll_ctx, seq=seq,
                             model=self._model_name):
                if self._runtime is not None:
                    self._runtime.swap_model(self._model_name, candidate)
                else:
                    candidate._gbdt._packed(0, -1)  # warm, like swap_model
            self._live = candidate
            self._seq = seq
            self._updates += 1
            now = time.monotonic()
            with self._wlock:
                # the trained rows' ledger entries were consumed at
                # snapshot time; entries present now belong to rows
                # ingested MID-update, which keep their true ingest
                # timestamps (staleness must not be reset to "now" by
                # the update that missed them).  The in-flight holdover
                # retires only HERE — at publication
                self._inflight_rows = 0
                self._inflight_oldest = None
                if kind == "append":
                    self._rows_since_append = 0
            self._last_rollover = now
            self._publish_staleness()
            ledger = dict(
                dispatches=c1["dispatches"] - c0["dispatches"],
                host_syncs=c1["host_syncs"] - c0["host_syncs"],
                compiles=c1["compiles"] - c0["compiles"])
            _obs.counter("continual_rollovers_total").inc()
            _obs.counter(f"continual_{kind}s_total").inc()
            _obs.event(f"continual_{kind}", seq=seq, rows=int(Xw.shape[0]),
                       **ledger)
            _obs.event("continual_rollover", mode=kind, seq=seq,
                       rows=int(Xw.shape[0]), trees=self._live.num_trees(),
                       **ledger)
            # the rollover's root span closes at publication — the
            # build/checkpoint/swap legs above are its children
            _trace.record_span("continual.rollover",
                               time.perf_counter() - t_roll, ctx=roll_ctx,
                               mode=kind, seq=seq, rows=int(Xw.shape[0]),
                               trees=self._live.num_trees(), outcome="ok",
                               **ledger)
            return kind

    def _clone(self) -> Booster:
        clone = Booster(model_str=self._live.model_to_string())
        clone._gbdt.cfg = self._live._gbdt.cfg
        return clone

    def _build_refit(self, Xw: np.ndarray, yw: np.ndarray) -> Booster:
        if self._refit_entry is None:
            why = refit_eligible(self._live._gbdt)
            raise ContinualError(
                f"device refit does not apply: {why} — configure "
                "append_trees= and drive append updates instead")
        clone = self._clone()
        refit_leaves(clone._gbdt, Xw, yw, entry=self._refit_entry)
        return clone

    def _build_append(self, Xw: np.ndarray, yw: np.ndarray) -> Booster:
        if self._append_trees <= 0:
            raise ContinualError("append update requested with "
                                 "append_trees=0")
        if self._ref_dataset is None:
            raise ContinualError(
                "append training needs the frozen bin mappers — pass "
                "reference= (the training Dataset or its save_binary "
                "cache)")
        from ..basic import Dataset
        from ..engine import train as _train

        ds = Dataset(Xw, label=yw, reference=self._ref_dataset,
                     params={"verbosity": -1})
        params = self._train_params()
        return _train(params, ds, num_boost_round=self._append_trees,
                      init_model=self._live)

    def _train_params(self) -> Dict[str, Any]:
        params = self._live._gbdt.cfg.to_dict()
        # the runner drives rounds/checkpoints/resume itself
        for k in ("num_iterations", "snapshot_freq", "resume",
                  "input_model", "metrics_file", "trace_file"):
            params.pop(k, None)
        return params

    # -- staleness -------------------------------------------------------
    def _publish_staleness(self) -> None:
        with self._wlock:
            rows = self._pending_rows + self._inflight_rows
            oldest = self._pending_ts[0][1] if self._pending_ts else None
            if self._inflight_oldest is not None:
                oldest = (self._inflight_oldest if oldest is None
                          else min(oldest, self._inflight_oldest))
        stale_s = (time.monotonic() - oldest) if oldest is not None else 0.0
        _obs.gauge("model_staleness_rows").set(float(rows))
        _obs.gauge("model_staleness_s").set(stale_s)
        if self._staleness_slo_s > 0:
            _obs.gauge("continual_staleness_exceeded").set(
                1.0 if stale_s > self._staleness_slo_s else 0.0)
