"""The continual leaf-refit executable (round 19; README "Continuous
training").

``Booster.refit`` is the reference's continued-training primitive
(GBDT::RefitTree via LGBM_BoosterRefit): keep every tree's STRUCTURE,
renew its leaf values on fresh data as
``new = decay * old + (1 - decay) * (-G_leaf / (H_leaf + lambda_l2))``,
with the per-tree gradients taken at the score accumulated from the
already-renewed earlier trees.  The host implementation walks trees one
at a time — T host traversals, T gradient pulls, T bincounts.  A
continual runner refits at ingest cadence beside a live serving loop, so
the update must cost like a predict, not like a training epoch: this
module fuses the WHOLE refit — the stacked leaf-index traversal, the
per-tree gradient/segment-sum/renewal scan, and the score accumulation —
into ONE donated jitted dispatch (the ``continual_refit_leaves`` jaxpr
contract pins it: zero collectives, donation consumed, transfer-free).

Semantics notes (deliberate, documented deviations are none — this IS
``Booster.refit``'s recipe, in f32 on device):

* the score starts at 0 over the EXPORT-form trees (init score folded
  into the first tree per class), exactly as ``Booster.refit`` runs on
  a ``model_to_string`` round-trip;
* a leaf no fresh row reaches (``sum_h == 0``) keeps its old value;
* multiclass ensembles renew tree ``t`` against class ``t % k``'s
  gradient column of the (nb, k) score plane — the reference's
  iter-major, class-minor RefitTree order (round 21; previously
  refused);
* sample weights enter through ``objective.get_gradients`` when the
  caller passes them (round 21); the default stays ``weight=None``,
  which is also what ``Booster.refit`` does without a ``weight=``.

Round 20 adds the BATCHED twin :func:`make_fleet_refit_entry` /
:func:`fleet_refit_leaves`: B independent k=1 models (a
``FleetBooster``'s lanes, or any same-config model list) refresh their
leaves in ONE donated dispatch — shared bucket-padded batch, per-lane
stacked packs, per-lane labels, the solo scan vmapped over the model
axis with the traversal input unmapped.

Envelope: non-linear leaves, no RF averaging — the same class of
eligibility the coalesced serving path checks.  Ineligible models
refuse loudly (``ContinualError``): silently refitting half a linear
model would be a correctness bug wearing a latency win.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..basic import LightGBMError
from ..ops import predict as predict_ops
from ..utils import sanitizer as _san


class ContinualError(LightGBMError):
    """An operation outside the continual runtime's envelope (linear
    leaves, multiclass device refit, missing mappers, ...)."""


@functools.lru_cache(maxsize=8)
def make_refit_entry(objective, decay: float, lam2: float, k: int = 1):
    """Build the jitted refit executable for one (objective, decay,
    lambda_l2, trees-per-iteration) configuration — memoized, so a
    runner (or repeated offline refits over the same objective instance)
    reuses ONE trace cache: every rollover reuses the compiled entry,
    zero retraces across rollovers, one compile per window bucket rung
    (the ``GBDT._get_convert_entry`` discipline, keyed on the factory
    args instead of the instance).

    Signature of the returned callable::

        new_leaf = run(leaf_value, shrinkage, x, sf, th, dl, mt, lc, rc,
                       nl, is_cat, cat_base, cat_nwords, cat_words,
                       label, active, weight=None)

    ``leaf_value`` (T, L) f32 is DONATED (callers pass a fresh upload,
    never the serving pack's cached buffer); ``x`` is a bucket-padded
    (nb, F) f32 batch with ``active`` masking the tail (None at exact
    fill), ``label`` the f32 targets padded alongside (class ids when
    ``k > 1``), ``weight`` optional padded f32 sample weights threaded
    to ``objective.get_gradients``.  Returns the renewed (T, L) f32
    leaf table.  ``k > 1`` runs the multiclass recipe: tree ``t``
    renews against class ``t % k``'s gradient column and accumulates
    into that class's score lane.
    """
    decay_f = jnp.float32(decay)
    keep_f = jnp.float32(1.0 - float(decay))
    lam2_f = jnp.float32(lam2)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(leaf_value, shrinkage, x, sf, th, dl, mt, lc, rc, nl,
            is_cat, cat_base, cat_nwords, cat_words, label, active,
            weight=None):
        # stacked leaf-index traversal: (N, T) -> (T, N), the same
        # vmapped walk the pred_leaf serving entry uses
        leaves = predict_ops.predict_leaf_values(
            x, sf, th, dl, mt, lc, rc, nl, is_cat=is_cat,
            cat_base=cat_base, cat_nwords=cat_nwords, cat_words=cat_words)
        leaves_t = leaves.T.astype(jnp.int32)  # (T, N)
        n_leaf = leaf_value.shape[1]
        actb = (jnp.ones(label.shape, jnp.bool_) if active is None
                else active)

        def renew(lv, leaf, shrink, g, h):
            g = jnp.where(actb, g.astype(jnp.float32), jnp.float32(0.0))
            h = jnp.where(actb, h.astype(jnp.float32), jnp.float32(0.0))
            sum_g = jnp.zeros((n_leaf,), jnp.float32).at[leaf].add(g)
            sum_h = jnp.zeros((n_leaf,), jnp.float32).at[leaf].add(h)
            new = -sum_g / (sum_h + lam2_f + jnp.float32(1e-15)) * shrink
            return jnp.where(sum_h > 0, decay_f * lv + keep_f * new, lv)

        if k == 1:
            def step(score, per_tree):
                lv, leaf, shrink = per_tree
                g, h = objective.get_gradients(score, label, weight)
                lv_new = renew(lv, leaf, shrink, g, h)
                # the renewed tree feeds the NEXT tree's gradients — the
                # reference's sequential RefitTree order, kept exactly
                score = score + jnp.where(actb, lv_new[leaf],
                                          jnp.float32(0.0))
                return score, lv_new

            score0 = jnp.zeros(label.shape, jnp.float32)
            _, new_leaf = jax.lax.scan(
                step, score0, (leaf_value, leaves_t, shrinkage))
            return new_leaf

        # multiclass: the (nb, k) score plane; tree t touches only its
        # class column c = t % k (the reference's iter-major order)
        cls = jnp.arange(leaf_value.shape[0], dtype=jnp.int32) % k

        def step_mc(score, per_tree):
            lv, leaf, shrink, c = per_tree
            g, h = objective.get_gradients(score, label, weight)
            lv_new = renew(lv, leaf, shrink,
                           jnp.take(g, c, axis=1), jnp.take(h, c, axis=1))
            score = score.at[:, c].add(
                jnp.where(actb, lv_new[leaf], jnp.float32(0.0)))
            return score, lv_new

        score0 = jnp.zeros((label.shape[0], k), jnp.float32)
        _, new_leaf = jax.lax.scan(
            step_mc, score0, (leaf_value, leaves_t, shrinkage, cls))
        return new_leaf

    return run


@functools.lru_cache(maxsize=8)
def make_fleet_refit_entry(objective, decay: float, lam2: float):
    """The BATCHED twin of :func:`make_refit_entry` for B independent
    k=1 models: the solo per-tree gradient/segment-sum/renewal scan
    vmapped over a leading model axis, with the bucket-padded traversal
    batch UNMAPPED (every lane walks the same rows through its OWN
    stacked pack).  One donated dispatch renews all B leaf tables.

    Signature of the returned callable::

        new_leaf = run(leaf_value, shrinkage, x, sf, th, dl, mt, lc, rc,
                       nl, label, active, weight=None)

    ``leaf_value`` (B, T, L) f32 is DONATED; the pack structure arrays
    are (B, T, m) stacked (lanes padded to the common T/m with
    single-leaf dummy trees whose shrinkage is 0 — their renewal and
    score contribution are exact zeros); ``label`` is (B, nb) per-lane
    targets over the SHARED (nb, F) batch; ``weight`` optionally
    (B, nb).  Categorical packs are outside the fleet envelope (the
    caller refuses them loudly).
    """
    decay_f = jnp.float32(decay)
    keep_f = jnp.float32(1.0 - float(decay))
    lam2_f = jnp.float32(lam2)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(leaf_value, shrinkage, x, sf, th, dl, mt, lc, rc, nl,
            label, active, weight=None):
        n_leaf = leaf_value.shape[2]
        actb = (jnp.ones(x.shape[:1], jnp.bool_) if active is None
                else active)  # (nb,), shared across lanes

        def lane(lv_b, shr_b, sf_b, th_b, dl_b, mt_b, lc_b, rc_b, nl_b,
                 lab_b, w_b):
            leaves = predict_ops.predict_leaf_values(
                x, sf_b, th_b, dl_b, mt_b, lc_b, rc_b, nl_b)
            leaves_t = leaves.T.astype(jnp.int32)

            def step(score, per_tree):
                lv, leaf, shrink = per_tree
                g, h = objective.get_gradients(score, lab_b, w_b)
                g = jnp.where(actb, g.astype(jnp.float32), jnp.float32(0.0))
                h = jnp.where(actb, h.astype(jnp.float32), jnp.float32(0.0))
                sum_g = jnp.zeros((n_leaf,), jnp.float32).at[leaf].add(g)
                sum_h = jnp.zeros((n_leaf,), jnp.float32).at[leaf].add(h)
                new = -sum_g / (sum_h + lam2_f + jnp.float32(1e-15)) * shrink
                lv_new = jnp.where(sum_h > 0, decay_f * lv + keep_f * new, lv)
                score = score + jnp.where(actb, lv_new[leaf],
                                          jnp.float32(0.0))
                return score, lv_new

            score0 = jnp.zeros(lab_b.shape, jnp.float32)
            _, new_leaf = jax.lax.scan(
                step, score0, (lv_b, leaves_t, shr_b))
            return new_leaf

        if weight is None:
            return jax.vmap(
                lambda lv, sh, a, b, c, d, e, f, g, lab:
                lane(lv, sh, a, b, c, d, e, f, g, lab, None)
            )(leaf_value, shrinkage, sf, th, dl, mt, lc, rc, nl, label)
        return jax.vmap(lane)(leaf_value, shrinkage, sf, th, dl, mt,
                              lc, rc, nl, label, weight)

    return run


def refit_eligible(gbdt) -> Optional[str]:
    """None when the device refit applies, else the human reason it
    does not (the runner surfaces it in the ContinualError).  Round 20:
    multiclass ensembles are eligible — the scan renews tree ``t``
    against class ``t % k`` (make_refit_entry's ``k`` argument)."""
    if gbdt.average_output:
        return "RF-averaged ensembles renew against scaled scores"
    s = gbdt._packed(0, -1)
    if s is None:
        return "the ensemble is empty"
    if s["_linear"]:
        return ("linear leaves carry per-leaf linear terms a leaf-value "
                "refit would silently drop")
    return None


def refit_leaves(gbdt, X: np.ndarray, label: np.ndarray, *,
                 weight: Optional[np.ndarray] = None, entry=None) -> int:
    """Refit ``gbdt``'s leaf values on ``(X, label)`` in ONE donated
    dispatch + ONE accounted sync, writing the renewed values back into
    the host trees and version-bumping the packed cache.  Returns the
    number of rows used.

    ``weight`` optionally carries per-row sample weights into the
    gradient call (round 21 — ``Booster.refit(weight=...)`` parity).
    ``entry`` is a prebuilt :func:`make_refit_entry` executable (the
    runner's cached one); None builds a throwaway (tests, one-shot
    offline use).  The donated leaf table is a FRESH upload — the cached
    serving pack's buffer is never donated, so in-flight readers of the
    current pack version are untouched until the version bump."""
    from ..models.gbdt import _predict_bucket

    why = refit_eligible(gbdt)
    if why is not None:
        raise ContinualError(f"device refit does not apply: {why} "
                             "(lightgbm_tpu/continual/refit.py envelope)")
    k = gbdt.num_tree_per_iteration
    if entry is None:
        entry = make_refit_entry(
            gbdt.objective, float(gbdt.cfg.refit_decay_rate),
            float(gbdt.cfg.lambda_l2), k=k)
    s = gbdt._packed(0, -1)
    trees = s["_trees"]
    # structural-mutation guard: the renewed tables are computed from
    # THIS pack snapshot and written back positionally — any concurrent
    # mutation (shuffle/rollback/leaf edit, all of which bump the pack
    # version) would silently attach them to the wrong trees, so the
    # write-back below verifies the version is unchanged and aborts loudly
    v0 = gbdt._pack_version
    X = np.asarray(X, np.float64)
    label = np.asarray(label, np.float64).ravel()
    if X.shape[0] != len(label):
        raise ValueError(f"refit_leaves: {X.shape[0]} rows but "
                         f"{len(label)} labels")
    n = X.shape[0]
    nb = _predict_bucket(n)
    x = gbdt._pad_rows(X, nb)
    active = gbdt._active_mask(n, nb)
    yb = np.zeros(nb, np.float32)
    yb[:n] = label
    wb = None
    if weight is not None:
        weight = np.asarray(weight, np.float64).ravel()
        if len(weight) != n:
            raise ValueError(f"refit_leaves: {n} rows but "
                             f"{len(weight)} weights")
        wb = np.zeros(nb, np.float32)
        wb[:n] = weight
    # fresh donated leaf table + the tiny per-tree shrinkage vector; the
    # pack's structure arrays ride along read-only
    lv0 = jnp.asarray(np.stack(
        [np.pad(np.asarray(t.leaf_value, np.float32),
                (0, s["leaf_value"].shape[1] - t.num_leaves))
         for t in trees]))
    shrink = jnp.asarray(np.asarray([t.shrinkage for t in trees],
                                    np.float32))
    _san.record_dispatch()
    out = entry(lv0, shrink, x, s["split_feature"], s["threshold"],
                s["default_left"], s["missing_type"], s["left_child"],
                s["right_child"], s["num_leaves"], s.get("is_cat"),
                s.get("cat_base"), s.get("cat_nwords"), s.get("cat_words"),
                jnp.asarray(yb), active,
                None if wb is None else jnp.asarray(wb))
    new_lv = np.asarray(_san.sync_pull(out), np.float64)
    # write back; the export-form first tree per class carries the folded
    # init score, so a delta-form model (init_scores separate)
    # re-separates it here — predict (init + sum of deltas) stays exactly
    # the renewed folded sum.  Mutation + version bump in ONE pack-lock
    # section: a pack build racing this (the model may already be
    # serving) retries at insert time, never caching a half-renewed pack
    # under the old version
    inits = [float(v) for v in (gbdt.init_scores or [0.0])]
    with gbdt._plock():
        if gbdt._pack_version != v0:
            raise ContinualError(
                "the ensemble mutated while the refit dispatch ran "
                f"(pack version {v0} -> {gbdt._pack_version}) — the "
                "renewed leaf tables no longer map onto the current "
                "trees; the write-back was aborted and the model is "
                "unchanged.  Serialize mutations with refits (the "
                "ContinualRunner's update lock does)")
        for i, t in enumerate(gbdt.models):
            vals = new_lv[i, : t.num_leaves].copy()
            if i < k and inits[i % k]:
                vals -= inits[i % k]
            t.leaf_value = vals
        gbdt._invalidate_pred_cache("continual_refit")
    return n


def _unwrap_lane(model):
    gbdt = getattr(model, "_gbdt", model)
    if not hasattr(gbdt, "_packed"):
        raise ContinualError(
            f"fleet_refit_leaves: {type(model).__name__} is not a "
            "Booster/GBDT lane")
    return gbdt


def fleet_refit_leaves(models, X: np.ndarray, labels: np.ndarray, *,
                       weights: Optional[np.ndarray] = None,
                       entry=None) -> int:
    """Refresh B models' leaf values in ONE donated dispatch + ONE
    accounted sync — the batched twin of :func:`refit_leaves` for a
    :class:`~lightgbm_tpu.models.fleet.FleetBooster` (or any list of
    same-config k=1 Boosters/GBDTs over the same feature space).

    ``labels`` is (B, n) per-lane targets over the SHARED ``X``;
    ``weights`` optionally (B, n).  Each lane's stacked pack is padded
    to the fleet's common (T, m) with zero-shrinkage single-leaf dummy
    trees (exact no-ops in the scan), the solo recipe runs vmapped over
    the model axis, and the renewed tables write back under each lane's
    pack lock with the solo version guard.  Returns the rows used."""
    from ..models.gbdt import _predict_bucket

    if hasattr(models, "boosters"):  # a FleetBooster
        models = models.boosters()
    lanes = [_unwrap_lane(m) for m in models]
    if not lanes:
        raise ContinualError("fleet_refit_leaves: no models")
    for i, g in enumerate(lanes):
        why = refit_eligible(g)
        if why is None and g.num_tree_per_iteration != 1:
            why = ("the batched twin is k=1 only — refit multiclass "
                   "models one at a time through refit_leaves")
        if why is not None:
            raise ContinualError(f"device refit does not apply to fleet "
                                 f"lane {i}: {why} "
                                 "(lightgbm_tpu/continual/refit.py)")
    cfg0 = lanes[0].cfg
    if entry is None:
        entry = make_fleet_refit_entry(
            lanes[0].objective, float(cfg0.refit_decay_rate),
            float(cfg0.lambda_l2))
    X = np.asarray(X, np.float64)
    labels = np.asarray(labels, np.float64)
    n = X.shape[0]
    if labels.shape != (len(lanes), n):
        raise ValueError(f"fleet_refit_leaves: labels must be "
                         f"({len(lanes)}, {n}), got {labels.shape}")
    if weights is not None:
        weights = np.asarray(weights, np.float64)
        if weights.shape != labels.shape:
            raise ValueError(f"fleet_refit_leaves: weights must match "
                             f"labels {labels.shape}, got {weights.shape}")
    # per-lane pack snapshots; pad every lane to the fleet-wide (T, m, L)
    # with zero-shrinkage dummy trees — their traversal lands every row
    # in leaf 0 of a zero table and their renewal multiplies by 0
    packs, versions = [], []
    for g in lanes:
        s = g._packed(0, -1)
        if s.get("is_cat") is not None:
            raise ContinualError(
                "fleet_refit_leaves: categorical packs are outside the "
                "fleet envelope — refit those models through refit_leaves")
        packs.append(s)
        versions.append(g._pack_version)
    t_max = max(s["T"] for s in packs)
    m_max = max(s["split_feature"].shape[1] for s in packs)
    l_max = max(s["leaf_value"].shape[1] for s in packs)
    b = len(lanes)

    def stack(key, dtype, width, fill=0):
        out = np.full((b, t_max, width), fill, dtype=dtype)
        for i, s in enumerate(packs):
            a = np.asarray(s[key])
            out[i, : a.shape[0], : a.shape[1]] = a
        return jnp.asarray(out)

    nl = np.ones((b, t_max), np.int32)
    lv0 = np.zeros((b, t_max, l_max), np.float32)
    shr = np.zeros((b, t_max), np.float32)
    for i, s in enumerate(packs):
        nl[i, : s["T"]] = np.asarray(s["num_leaves"])
        for j, t in enumerate(s["_trees"]):
            lv0[i, j, : t.num_leaves] = np.asarray(t.leaf_value, np.float32)
            shr[i, j] = t.shrinkage
    nb = _predict_bucket(n)
    x = lanes[0]._pad_rows(X, nb)
    active = lanes[0]._active_mask(n, nb)
    yb = np.zeros((b, nb), np.float32)
    yb[:, :n] = labels
    wb = None
    if weights is not None:
        wb = np.zeros((b, nb), np.float32)
        wb[:, :n] = weights
    _san.record_dispatch()
    out = entry(jnp.asarray(lv0), jnp.asarray(shr), x,
                stack("split_feature", np.int32, m_max),
                stack("threshold", np.float32, m_max),
                stack("default_left", bool, m_max),
                stack("missing_type", np.int32, m_max),
                stack("left_child", np.int32, m_max, fill=-1),
                stack("right_child", np.int32, m_max, fill=-1),
                jnp.asarray(nl), jnp.asarray(yb), active,
                None if wb is None else jnp.asarray(wb))
    new_lv = np.asarray(_san.sync_pull(out), np.float64)
    for i, g in enumerate(lanes):
        inits = [float(v) for v in (g.init_scores or [0.0])]
        with g._plock():
            if g._pack_version != versions[i]:
                raise ContinualError(
                    f"fleet lane {i} mutated while the batched refit "
                    f"dispatch ran (pack version {versions[i]} -> "
                    f"{g._pack_version}); lanes 0..{i - 1} are renewed, "
                    f"lane {i} on are unchanged — serialize mutations "
                    "with refits")
            for j, t in enumerate(g.models):
                vals = new_lv[i, j, : t.num_leaves].copy()
                if j == 0 and inits[0]:
                    vals -= inits[0]
                t.leaf_value = vals
            g._invalidate_pred_cache("continual_refit")
    return n


def audit_refit_fn(objective=None):
    """The jitted callable one continual refit dispatches — the
    ``continual_refit_leaves`` jaxpr-audit contract traces THIS builder
    (analysis/contracts.py), so a refit path that grew a second
    executable, a collective, or an in-trace transfer fails the audit
    statically rather than burning a chip session."""
    if objective is None:
        from ..config import Config
        from ..objectives import create_objective

        objective = create_objective(Config.from_dict(
            {"objective": "regression"}))
    return make_refit_entry(objective, decay=0.9, lam2=0.0)
