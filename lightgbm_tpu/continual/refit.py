"""The continual leaf-refit executable (round 19; README "Continuous
training").

``Booster.refit`` is the reference's continued-training primitive
(GBDT::RefitTree via LGBM_BoosterRefit): keep every tree's STRUCTURE,
renew its leaf values on fresh data as
``new = decay * old + (1 - decay) * (-G_leaf / (H_leaf + lambda_l2))``,
with the per-tree gradients taken at the score accumulated from the
already-renewed earlier trees.  The host implementation walks trees one
at a time — T host traversals, T gradient pulls, T bincounts.  A
continual runner refits at ingest cadence beside a live serving loop, so
the update must cost like a predict, not like a training epoch: this
module fuses the WHOLE refit — the stacked leaf-index traversal, the
per-tree gradient/segment-sum/renewal scan, and the score accumulation —
into ONE donated jitted dispatch (the ``continual_refit_leaves`` jaxpr
contract pins it: zero collectives, donation consumed, transfer-free).

Semantics notes (deliberate, documented deviations are none — this IS
``Booster.refit``'s recipe, in f32 on device):

* the score starts at 0 over the EXPORT-form trees (init score folded
  into tree 0), exactly as ``Booster.refit`` runs on a
  ``model_to_string`` round-trip;
* a leaf no fresh row reaches (``sum_h == 0``) keeps its old value;
* weights are not consulted (``Booster.refit`` passes ``weight=None``
  to the objective too).

Envelope: single-output objectives (``num_tree_per_iteration == 1``),
non-linear leaves, no RF averaging — the same class of eligibility the
coalesced serving path checks.  Ineligible models refuse loudly
(``ContinualError``): silently refitting half a linear model would be a
correctness bug wearing a latency win.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..basic import LightGBMError
from ..ops import predict as predict_ops
from ..utils import sanitizer as _san


class ContinualError(LightGBMError):
    """An operation outside the continual runtime's envelope (linear
    leaves, multiclass device refit, missing mappers, ...)."""


@functools.lru_cache(maxsize=8)
def make_refit_entry(objective, decay: float, lam2: float):
    """Build the jitted refit executable for one (objective, decay,
    lambda_l2) configuration — memoized, so a runner (or repeated offline
    refits over the same objective instance) reuses ONE trace cache:
    every rollover reuses the compiled entry, zero retraces across
    rollovers, one compile per window bucket rung (the
    ``GBDT._get_convert_entry`` discipline, keyed on the factory args
    instead of the instance).

    Signature of the returned callable::

        new_leaf = run(leaf_value, shrinkage, x, sf, th, dl, mt, lc, rc,
                       nl, is_cat, cat_base, cat_nwords, cat_words,
                       label, active)

    ``leaf_value`` (T, L) f32 is DONATED (callers pass a fresh upload,
    never the serving pack's cached buffer); ``x`` is a bucket-padded
    (nb, F) f32 batch with ``active`` masking the tail (None at exact
    fill), ``label`` the f32 targets padded alongside.  Returns the
    renewed (T, L) f32 leaf table.
    """
    decay_f = jnp.float32(decay)
    keep_f = jnp.float32(1.0 - float(decay))
    lam2_f = jnp.float32(lam2)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(leaf_value, shrinkage, x, sf, th, dl, mt, lc, rc, nl,
            is_cat, cat_base, cat_nwords, cat_words, label, active):
        # stacked leaf-index traversal: (N, T) -> (T, N), the same
        # vmapped walk the pred_leaf serving entry uses
        leaves = predict_ops.predict_leaf_values(
            x, sf, th, dl, mt, lc, rc, nl, is_cat=is_cat,
            cat_base=cat_base, cat_nwords=cat_nwords, cat_words=cat_words)
        leaves_t = leaves.T.astype(jnp.int32)  # (T, N)
        n_leaf = leaf_value.shape[1]
        actb = (jnp.ones(label.shape, jnp.bool_) if active is None
                else active)

        def step(score, per_tree):
            lv, leaf, shrink = per_tree
            g, h = objective.get_gradients(score, label, None)
            g = jnp.where(actb, g.astype(jnp.float32), jnp.float32(0.0))
            h = jnp.where(actb, h.astype(jnp.float32), jnp.float32(0.0))
            sum_g = jnp.zeros((n_leaf,), jnp.float32).at[leaf].add(g)
            sum_h = jnp.zeros((n_leaf,), jnp.float32).at[leaf].add(h)
            new = -sum_g / (sum_h + lam2_f + jnp.float32(1e-15)) * shrink
            lv_new = jnp.where(sum_h > 0, decay_f * lv + keep_f * new, lv)
            # the renewed tree feeds the NEXT tree's gradients — the
            # reference's sequential RefitTree order, kept exactly
            score = score + jnp.where(actb, lv_new[leaf], jnp.float32(0.0))
            return score, lv_new

        score0 = jnp.zeros(label.shape, jnp.float32)
        _, new_leaf = jax.lax.scan(
            step, score0, (leaf_value, leaves_t, shrinkage))
        return new_leaf

    return run


def refit_eligible(gbdt) -> Optional[str]:
    """None when the device refit applies, else the human reason it
    does not (the runner surfaces it in the ContinualError)."""
    if gbdt.num_tree_per_iteration != 1:
        return ("multiclass ensembles refit per-class scores the device "
                "scan does not model yet")
    if gbdt.average_output:
        return "RF-averaged ensembles renew against scaled scores"
    s = gbdt._packed(0, -1)
    if s is None:
        return "the ensemble is empty"
    if s["_linear"]:
        return ("linear leaves carry per-leaf linear terms a leaf-value "
                "refit would silently drop")
    return None


def refit_leaves(gbdt, X: np.ndarray, label: np.ndarray, *,
                 entry=None) -> int:
    """Refit ``gbdt``'s leaf values on ``(X, label)`` in ONE donated
    dispatch + ONE accounted sync, writing the renewed values back into
    the host trees and version-bumping the packed cache.  Returns the
    number of rows used.

    ``entry`` is a prebuilt :func:`make_refit_entry` executable (the
    runner's cached one); None builds a throwaway (tests, one-shot
    offline use).  The donated leaf table is a FRESH upload — the cached
    serving pack's buffer is never donated, so in-flight readers of the
    current pack version are untouched until the version bump."""
    from ..models.gbdt import _predict_bucket

    why = refit_eligible(gbdt)
    if why is not None:
        raise ContinualError(f"device refit does not apply: {why} "
                             "(lightgbm_tpu/continual/refit.py envelope)")
    if entry is None:
        entry = make_refit_entry(
            gbdt.objective, float(gbdt.cfg.refit_decay_rate),
            float(gbdt.cfg.lambda_l2))
    s = gbdt._packed(0, -1)
    trees = s["_trees"]
    # structural-mutation guard: the renewed tables are computed from
    # THIS pack snapshot and written back positionally — any concurrent
    # mutation (shuffle/rollback/leaf edit, all of which bump the pack
    # version) would silently attach them to the wrong trees, so the
    # write-back below verifies the version is unchanged and aborts loudly
    v0 = gbdt._pack_version
    X = np.asarray(X, np.float64)
    label = np.asarray(label, np.float64).ravel()
    if X.shape[0] != len(label):
        raise ValueError(f"refit_leaves: {X.shape[0]} rows but "
                         f"{len(label)} labels")
    n = X.shape[0]
    nb = _predict_bucket(n)
    x = gbdt._pad_rows(X, nb)
    active = gbdt._active_mask(n, nb)
    yb = np.zeros(nb, np.float32)
    yb[:n] = label
    # fresh donated leaf table + the tiny per-tree shrinkage vector; the
    # pack's structure arrays ride along read-only
    lv0 = jnp.asarray(np.stack(
        [np.pad(np.asarray(t.leaf_value, np.float32),
                (0, s["leaf_value"].shape[1] - t.num_leaves))
         for t in trees]))
    shrink = jnp.asarray(np.asarray([t.shrinkage for t in trees],
                                    np.float32))
    _san.record_dispatch()
    out = entry(lv0, shrink, x, s["split_feature"], s["threshold"],
                s["default_left"], s["missing_type"], s["left_child"],
                s["right_child"], s["num_leaves"], s.get("is_cat"),
                s.get("cat_base"), s.get("cat_nwords"), s.get("cat_words"),
                jnp.asarray(yb), active)
    new_lv = np.asarray(_san.sync_pull(out), np.float64)
    # write back; export-form tree 0 carries the folded init score, so a
    # delta-form model (init_scores separate) re-separates it here —
    # predict (init + sum of deltas) stays exactly the renewed folded sum.
    # Mutation + version bump in ONE pack-lock section: a pack build
    # racing this (the model may already be serving) retries at insert
    # time, never caching a half-renewed pack under the old version
    init = float(gbdt.init_scores[0]) if gbdt.init_scores else 0.0
    with gbdt._plock():
        if gbdt._pack_version != v0:
            raise ContinualError(
                "the ensemble mutated while the refit dispatch ran "
                f"(pack version {v0} -> {gbdt._pack_version}) — the "
                "renewed leaf tables no longer map onto the current "
                "trees; the write-back was aborted and the model is "
                "unchanged.  Serialize mutations with refits (the "
                "ContinualRunner's update lock does)")
        for i, t in enumerate(gbdt.models):
            vals = new_lv[i, : t.num_leaves].copy()
            if i == 0 and init:
                vals -= init
            t.leaf_value = vals
        gbdt._invalidate_pred_cache("continual_refit")
    return n


def audit_refit_fn(objective=None):
    """The jitted callable one continual refit dispatches — the
    ``continual_refit_leaves`` jaxpr-audit contract traces THIS builder
    (analysis/contracts.py), so a refit path that grew a second
    executable, a collective, or an in-trace transfer fails the audit
    statically rather than burning a chip session."""
    if objective is None:
        from ..config import Config
        from ..objectives import create_objective

        objective = create_objective(Config.from_dict(
            {"objective": "regression"}))
    return make_refit_entry(objective, decay=0.9, lam2=0.0)
