"""Training callbacks.

Reference: python-package/lightgbm/callback.py — CallbackEnv,
log_evaluation, record_evaluation, reset_parameter, early_stopping
(class-based stateful implementation), EarlyStopException, callback
`.order` / `.before_iteration` ordering contract.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from .utils.log import log_info, log_warning


class EarlyStopException(Exception):
    """reference: EarlyStopException(best_iteration, best_score)."""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


@dataclass
class CallbackEnv:
    model: Any
    params: Dict[str, Any]
    iteration: int
    begin_iteration: int
    end_iteration: int
    evaluation_result_list: List[Tuple[str, str, float, bool]]


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """reference: callback.log_evaluation."""

    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and (env.iteration + 1) % period == 0:
            result = "\t".join(
                _format_eval_result(x, show_stdv) for x in env.evaluation_result_list
            )
            log_info(f"[{env.iteration + 1}]\t{result}")

    _callback.order = 10  # type: ignore[attr-defined]
    _callback.before_iteration = False  # type: ignore[attr-defined]
    return _callback


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    # cv result with stdv
    if show_stdv:
        return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
    return f"{value[0]}'s {value[1]}: {value[2]:g}"


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    """reference: callback.record_evaluation."""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _init(env: CallbackEnv) -> None:
        eval_result.clear()
        for item in env.evaluation_result_list:
            name, metric = item[0], item[1]
            eval_result.setdefault(name, {}).setdefault(metric, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for item in env.evaluation_result_list:
            name, metric, val = item[0], item[1], item[2]
            eval_result.setdefault(name, {}).setdefault(metric, []).append(val)
            if len(item) >= 5:  # cv stdv
                eval_result[name].setdefault(f"{metric}-stdv", []).append(item[4])

    _callback.order = 20  # type: ignore[attr-defined]
    _callback.before_iteration = False  # type: ignore[attr-defined]
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """Per-iteration parameter schedules (reference: callback.reset_parameter).
    Values may be lists (indexed by iteration) or callables iteration->value."""

    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(f"Length of list {key!r} has to equal to 'num_boost_round'.")
                new_param = value[env.iteration - env.begin_iteration]
            else:
                new_param = value(env.iteration - env.begin_iteration)
            new_params[key] = new_param
        if new_params:
            env.model._gbdt.cfg.update(new_params)
            env.model._gbdt.reset_split_params()
            env.params.update(new_params)

    _callback.before_iteration = True  # type: ignore[attr-defined]
    _callback.order = 10  # type: ignore[attr-defined]
    return _callback


class _EarlyStoppingCallback:
    """reference: callback._EarlyStoppingCallback."""

    def __init__(self, stopping_rounds: int, first_metric_only: bool = False,
                 verbose: bool = True, min_delta: float = 0.0):
        if stopping_rounds <= 0:
            raise ValueError("stopping_rounds should be greater than zero.")
        self.stopping_rounds = stopping_rounds
        self.first_metric_only = first_metric_only
        self.verbose = verbose
        self.min_delta = min_delta
        self.order = 30
        self.before_iteration = False
        self.enabled = True
        self._reset_storages()

    def _reset_storages(self) -> None:
        self.best_score: List[float] = []
        self.best_iter: List[int] = []
        self.best_score_list: List[Any] = []
        self.cmp_op: List[Callable[[float, float], bool]] = []
        self.first_metric = ""
        self._initialized = False

    def _init(self, env: CallbackEnv) -> None:
        self._initialized = True
        if not env.evaluation_result_list:
            self.enabled = False
            log_warning("Early stopping is only available if at least one validation set is provided.")
            return
        if self.verbose:
            log_info(f"Training until validation scores don't improve for {self.stopping_rounds} rounds")
        self.first_metric = env.evaluation_result_list[0][1]
        for item in env.evaluation_result_list:
            higher_better = item[3]
            self.best_iter.append(0)
            if higher_better:
                self.best_score.append(float("-inf"))
                self.cmp_op.append(lambda cur, best: cur > best + self.min_delta)
            else:
                self.best_score.append(float("inf"))
                self.cmp_op.append(lambda cur, best: cur < best - self.min_delta)
            self.best_score_list.append(None)

    def __call__(self, env: CallbackEnv) -> None:
        if not self._initialized:
            self._init(env)
        if not self.enabled:
            return
        # skip the training-set entries (reference: early stopping only
        # watches validation sets unless only train is available)
        for i, item in enumerate(env.evaluation_result_list):
            name, metric, score = item[0], item[1], item[2]
            if self.best_score_list[i] is None or self.cmp_op[i](score, self.best_score[i]):
                self.best_score[i] = score
                self.best_iter[i] = env.iteration
                self.best_score_list[i] = env.evaluation_result_list
            if self.first_metric_only and metric != self.first_metric:
                continue
            if name == "training":
                continue
            if env.iteration - self.best_iter[i] >= self.stopping_rounds:
                if self.verbose:
                    log_info(
                        f"Early stopping, best iteration is:\n[{self.best_iter[i] + 1}]\t"
                        + "\t".join(_format_eval_result(x) for x in self.best_score_list[i])
                    )
                raise EarlyStopException(self.best_iter[i], self.best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if self.verbose:
                    log_info(
                        f"Did not meet early stopping. Best iteration is:\n[{self.best_iter[i] + 1}]\t"
                        + "\t".join(_format_eval_result(x) for x in self.best_score_list[i])
                    )
                raise EarlyStopException(self.best_iter[i], self.best_score_list[i])


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: float = 0.0) -> _EarlyStoppingCallback:
    """reference: callback.early_stopping."""
    return _EarlyStoppingCallback(stopping_rounds, first_metric_only, verbose, min_delta)
