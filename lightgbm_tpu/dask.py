"""Distributed sklearn-style estimators — the Dask-module analogue.

Reference: python-package/lightgbm/dask.py (DaskLGBMClassifier /
DaskLGBMRegressor / DaskLGBMRanker over dask collections + a Client).
That module's whole job is orchestration: align data partitions to
workers, open ports, build the `machines` list, run plain training on
every worker with network params, return the rank-0 model wrapped as the
matching sklearn estimator.

TPU-native redesign: there is no dask dependency in this image, and the
multi-host story is `jax.distributed` (see parallel/distributed.py), so
these estimators wrap `parallel/launcher.py::train_distributed` — workers
are processes wired through the jax coordinator, each receiving only its
row shard (`pre_partition` semantics), collectives run over XLA.  `fit`
accepts plain numpy/array-likes instead of dask collections; everything
else (constructor params, predict/predict_proba surface, fitted
attributes) matches the local sklearn wrappers, so
`DaskLGBMRegressor(...).fit(X, y)` is a drop-in for the reference's
workflow minus the Client plumbing.

Like the reference's module, `fit` accepts eval_set: each eval set is
row-sharded across ranks alongside the training data, evaluated through
the pre_partition synced metric path (every rank sees identical values —
Network::GlobalSyncUpBySum analogue), and `early_stopping_rounds` fires
identically on every rank (reference: dask.py _train(eval_set...)).
"""

from __future__ import annotations

import numpy as np

from .basic import LightGBMError
from .parallel.launcher import train_distributed
from .sklearn import LGBMClassifier, LGBMRanker, LGBMRegressor

__all__ = [
    "DaskLGBMClassifier",
    "DaskLGBMRegressor",
    "DaskLGBMRanker",
]


def _normalize_eval_set(eval_set):
    """One (X, y) tuple or a list of them -> list of (ndarray, 1-D ndarray)."""
    if eval_set is None:
        return None
    if isinstance(eval_set, tuple):
        eval_set = [eval_set]
    return [(np.asarray(Xe), np.asarray(ye).ravel()) for Xe, ye in eval_set]


class _DistributedFitMixin:
    """Shared distributed-fit plumbing (reference: dask.py _train).

    Declares the FULL LGBMModel parameter signature (sklearn's get_params
    introspects ``type(self).__init__`` — a bare ``**kwargs`` constructor
    would hide every training parameter from it, silently training with
    defaults; the reference's dask module re-declares the signature for
    the same reason), plus the two orchestration knobs."""

    def __init__(
        self,
        boosting_type: str = "gbdt",
        num_leaves: int = 31,
        max_depth: int = -1,
        learning_rate: float = 0.1,
        n_estimators: int = 100,
        subsample_for_bin: int = 200000,
        objective=None,
        class_weight=None,
        min_split_gain: float = 0.0,
        min_child_weight: float = 1e-3,
        min_child_samples: int = 20,
        subsample: float = 1.0,
        subsample_freq: int = 0,
        colsample_bytree: float = 1.0,
        reg_alpha: float = 0.0,
        reg_lambda: float = 0.0,
        random_state=None,
        n_jobs=None,
        importance_type: str = "split",
        num_machines: int = 2,
        launch_timeout_s: int = 600,
        **kwargs,
    ):
        self.num_machines = num_machines
        self.launch_timeout_s = launch_timeout_s
        super().__init__(
            boosting_type=boosting_type, num_leaves=num_leaves,
            max_depth=max_depth, learning_rate=learning_rate,
            n_estimators=n_estimators, subsample_for_bin=subsample_for_bin,
            objective=objective, class_weight=class_weight,
            min_split_gain=min_split_gain, min_child_weight=min_child_weight,
            min_child_samples=min_child_samples, subsample=subsample,
            subsample_freq=subsample_freq, colsample_bytree=colsample_bytree,
            reg_alpha=reg_alpha, reg_lambda=reg_lambda,
            random_state=random_state, n_jobs=n_jobs,
            importance_type=importance_type, **kwargs,
        )

    def _fit_distributed(self, X, y, sample_weight=None, group=None,
                         eval_set=None, eval_names=None,
                         eval_sample_weight=None, eval_group=None,
                         eval_metric=None, early_stopping_rounds=None):
        params = self._process_params(self._default_objective())
        if params.get("objective") == "none":
            raise LightGBMError(
                "custom objective callables are not supported by the "
                "distributed estimators (the objective must be "
                "reconstructable by name on every worker)")
        # estimator-orchestration params must not leak into training config
        for k in ("num_machines", "launch_timeout_s"):
            params.pop(k, None)
        if eval_metric is not None:
            if callable(eval_metric):
                raise LightGBMError(
                    "custom eval_metric callables are not supported by the "
                    "distributed estimators (metrics must be "
                    "reconstructable by name on every worker)")
            params["metric"] = eval_metric
        eval_set = _normalize_eval_set(eval_set)
        booster, _ = train_distributed(
            params,
            np.asarray(X),
            np.asarray(y).ravel(),
            self.n_estimators,
            num_machines=self.num_machines,
            weight=(None if sample_weight is None
                    else np.asarray(sample_weight, np.float64).ravel()),
            group=group,
            eval_set=eval_set,
            eval_names=eval_names,
            eval_weight=eval_sample_weight,
            eval_group=eval_group,
            early_stopping_rounds=early_stopping_rounds,
            timeout_s=self.launch_timeout_s,
        )
        self._Booster = booster
        self._fobj = None
        self._feval = None
        self._evals_result = getattr(booster, "_distributed_evals_result", {})
        self._n_features = booster.num_feature()
        self.n_features_in_ = self._n_features
        self.fitted_ = True
        self._best_iteration = booster.best_iteration
        self._best_score = booster.best_score
        return self


class DaskLGBMRegressor(_DistributedFitMixin, LGBMRegressor):
    """reference: dask.py DaskLGBMRegressor."""


    def fit(self, X, y, sample_weight=None, eval_set=None, eval_names=None,
            eval_sample_weight=None, eval_metric=None,
            early_stopping_rounds=None) -> "DaskLGBMRegressor":
        return self._fit_distributed(
            X, y, sample_weight=sample_weight, eval_set=eval_set,
            eval_names=eval_names, eval_sample_weight=eval_sample_weight,
            eval_metric=eval_metric,
            early_stopping_rounds=early_stopping_rounds)


class DaskLGBMClassifier(_DistributedFitMixin, LGBMClassifier):
    """reference: dask.py DaskLGBMClassifier."""


    def fit(self, X, y, sample_weight=None, eval_set=None, eval_names=None,
            eval_sample_weight=None, eval_metric=None,
            early_stopping_rounds=None) -> "DaskLGBMClassifier":
        y_enc = self._prepare_class_labels(y)
        if self.class_weight is not None and self.n_classes_ >= 2:
            # the local wrapper folds class_weight into sample weights
            # (LGBMModel.fit); mirror it here so the distributed model
            # matches rather than silently ignoring the option
            from sklearn.utils.class_weight import compute_sample_weight

            cw = compute_sample_weight(self.class_weight, y_enc)
            sample_weight = (cw if sample_weight is None
                             else np.asarray(sample_weight,
                                             np.float64).ravel() * cw)
        if eval_set is not None:
            eval_set = [(Xe, self._le.transform(ye))
                        for Xe, ye in _normalize_eval_set(eval_set)]
        return self._fit_distributed(
            X, y_enc, sample_weight=sample_weight, eval_set=eval_set,
            eval_names=eval_names, eval_sample_weight=eval_sample_weight,
            eval_metric=eval_metric,
            early_stopping_rounds=early_stopping_rounds)


class DaskLGBMRanker(_DistributedFitMixin, LGBMRanker):
    """reference: dask.py DaskLGBMRanker (group sizes required; shards snap
    to query boundaries — the launcher keeps queries whole per worker, as
    the reference keeps dask partitions whole)."""


    def fit(self, X, y, group=None, sample_weight=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_group=None,
            eval_metric=None, early_stopping_rounds=None,
            eval_at=(1, 2, 3, 4, 5)) -> "DaskLGBMRanker":
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError(
                "eval_group must be provided with eval_set for ranking")
        self._other_params["eval_at"] = list(eval_at)
        setattr(self, "eval_at", list(eval_at))
        return self._fit_distributed(
            X, y, sample_weight=sample_weight,
            group=np.asarray(group, np.int64),
            eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight, eval_group=eval_group,
            eval_metric=eval_metric,
            early_stopping_rounds=early_stopping_rounds)
