"""TreeSHAP feature contributions.

Reference: Tree::PredictContrib / TreeSHAP recursion in src/io/tree.cpp
(Lundberg & Lee Algorithm 2 over internal_value/weight/count fields), exposed
through LGBM_BoosterPredict* with C_API_PREDICT_CONTRIB.

Host-side numpy implementation (prediction-time tooling, not a training hot
path; a batched device version is a later optimization).
"""

from __future__ import annotations

from typing import List

import numpy as np


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0, one_fraction=0.0, pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight


def _extend_path(path: List[_PathElement], unique_depth, zero_fraction, one_fraction, feature_index):
    path.append(_PathElement(feature_index, zero_fraction, one_fraction,
                             1.0 if unique_depth == 0 else 0.0))
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) / (unique_depth + 1)
        path[i].pweight = zero_fraction * path[i].pweight * (unique_depth - i) / (unique_depth + 1)


def _unwind_path(path: List[_PathElement], unique_depth, path_index):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (unique_depth + 1) / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction * (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = path[i].pweight * (unique_depth + 1) / (zero_fraction * (unique_depth - i))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction
    path.pop()


def _unwound_path_sum(path: List[_PathElement], unique_depth, path_index):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (unique_depth + 1) / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * ((unique_depth - i) / (unique_depth + 1))
        else:
            total += path[i].pweight / (zero_fraction * ((unique_depth - i) / (unique_depth + 1)))
    return total


def tree_shap_one(tree, x: np.ndarray, phi: np.ndarray) -> None:
    """SHAP contributions of one tree for one row, accumulated into phi
    (length n_features + 1; last slot = expected value/bias)."""
    if tree.num_leaves <= 1:
        phi[-1] += tree.leaf_value[0]
        return

    dl = tree.default_left()
    # node "cover" = internal_count, leaf cover = leaf_count
    def node_count(node):
        return tree.internal_count[node] if node >= 0 else tree.leaf_count[-node - 1]

    def node_value(node):
        return tree.internal_value[node] if node >= 0 else tree.leaf_value[-node - 1]

    phi[-1] += _expected_value(tree)

    is_cat = tree.is_categorical_node()
    missing_type = (tree.decision_type.astype(np.int32) >> 2) & 3

    def decision(node):
        """Same semantics as Tree.predict (incl. missing_type Zero routing)."""
        f = tree.split_feature[node]
        v = x[f]
        if is_cat[node]:
            left = tree.cat_decision_left(node, v)
        else:
            mt = missing_type[node]
            if np.isnan(v) and mt == 2:
                left = dl[node]
            elif mt == 1 and (np.isnan(v) or abs(v) <= 1e-35):
                left = dl[node]
            else:
                left = (0.0 if np.isnan(v) else v) <= tree.threshold[node]
        return tree.left_child[node] if left else tree.right_child[node]

    def recurse(node, path: List[_PathElement], parent_zero, parent_one, parent_idx):
        unique_depth = len(path)
        path = [
            _PathElement(p.feature_index, p.zero_fraction, p.one_fraction, p.pweight) for p in path
        ]
        _extend_path(path, unique_depth, parent_zero, parent_one, parent_idx)
        if node < 0:  # leaf
            leaf = -node - 1
            for i in range(1, unique_depth + 1):
                w = _unwound_path_sum(path, unique_depth, i)
                el = path[i]
                phi[el.feature_index] += w * (el.one_fraction - el.zero_fraction) * tree.leaf_value[leaf]
            return
        hot = decision(node)
        cold = tree.right_child[node] if hot == tree.left_child[node] else tree.left_child[node]
        hot_frac = node_count(hot) / max(node_count(node), 1)
        cold_frac = node_count(cold) / max(node_count(node), 1)
        incoming_zero, incoming_one = 1.0, 1.0
        path_index = -1
        f = tree.split_feature[node]
        for i in range(1, unique_depth + 1):
            if path[i].feature_index == f:
                path_index = i
                break
        if path_index >= 0:
            incoming_zero = path[path_index].zero_fraction
            incoming_one = path[path_index].one_fraction
            _unwind_path(path, unique_depth, path_index)
            unique_depth -= 1
        recurse(hot, path, hot_frac * incoming_zero, incoming_one, f)
        recurse(cold, path, cold_frac * incoming_zero, 0.0, f)

    recurse(0, [], 1.0, 1.0, -1)


def _expected_value(tree) -> float:
    """Weighted average of leaf values (the bias term)."""
    counts = tree.leaf_count[: tree.num_leaves].astype(np.float64)
    total = counts.sum()
    if total <= 0:
        return float(np.mean(tree.leaf_value[: tree.num_leaves]))
    return float(np.sum(tree.leaf_value[: tree.num_leaves] * counts) / total)


def tree_shap_ensemble(trees, X: np.ndarray, num_class: int = 1) -> np.ndarray:
    """Contributions (N, (F+1)) or (N, K*(F+1)) for multiclass, matching the
    reference's pred_contrib output layout."""
    n, f = X.shape
    if num_class <= 1:
        out = np.zeros((n, f + 1), dtype=np.float64)
        for t in trees:
            for i in range(n):
                tree_shap_one(t, X[i], out[i])
        return out
    out = np.zeros((n, num_class, f + 1), dtype=np.float64)
    for ti, t in enumerate(trees):
        c = ti % num_class
        for i in range(n):
            tree_shap_one(t, X[i], out[i, c])
    return out.reshape(n, num_class * (f + 1))
