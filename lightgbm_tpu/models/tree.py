"""Host-side tree model: trimmed arrays + LightGBM text-format serialization.

Reference: src/io/tree.cpp / include/LightGBM/tree.h (Tree::ToString,
Tree::Split recording real-valued thresholds from bin uppers) and
src/boosting/gbdt_model_text.cpp (the `.txt` model format — the interop
contract per SURVEY.md §6.4).

decision_type bitfield (reference: include/LightGBM/tree.h):
  bit 0: categorical;  bit 1: default_left;  bits 2-3: missing type
  (0 = None, 1 = Zero, 2 = NaN).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2
_MISSING_TYPE_SHIFT = 2  # reference: kMissingTypeMask >> positions


@dataclass
class Tree:
    """One decision tree in host numpy arrays (trimmed to actual size)."""

    num_leaves: int
    split_feature: np.ndarray  # (M,) i32, M = num_leaves - 1
    threshold: np.ndarray  # (M,) f64 — real-valued
    threshold_bin: Optional[np.ndarray]  # (M,) i32 binned; None for loaded models
    decision_type: np.ndarray  # (M,) u8
    split_gain: np.ndarray  # (M,) f32
    left_child: np.ndarray  # (M,) i32
    right_child: np.ndarray  # (M,) i32
    internal_value: np.ndarray  # (M,) f64
    internal_weight: np.ndarray  # (M,) f64
    internal_count: np.ndarray  # (M,) i64
    leaf_value: np.ndarray  # (L,) f64
    leaf_weight: np.ndarray  # (L,) f64
    leaf_count: np.ndarray  # (L,) i64
    shrinkage: float = 1.0
    # categorical split storage (reference: cat_boundaries_/cat_threshold_)
    num_cat: int = 0
    cat_boundaries: np.ndarray = field(default_factory=lambda: np.zeros(1, np.int32))
    cat_threshold: np.ndarray = field(default_factory=lambda: np.zeros(0, np.uint32))
    is_linear: bool = False

    @property
    def num_internal(self) -> int:
        return max(self.num_leaves - 1, 0)

    def default_left(self) -> np.ndarray:
        return (self.decision_type & K_DEFAULT_LEFT_MASK) != 0

    def apply_shrinkage(self, rate: float) -> None:
        """reference: Tree::Shrinkage."""
        self.leaf_value = self.leaf_value * rate
        self.internal_value = self.internal_value * rate
        self.shrinkage *= rate

    # ------------------------------------------------------------------
    def predict(self, x: np.ndarray) -> np.ndarray:
        """Scalar reference predict on raw values (numpy; used by tests and
        small-batch paths — the hot path is ops/predict.py on device)."""
        x = np.asarray(x, dtype=np.float64)
        n = x.shape[0]
        out = np.empty(n, dtype=np.float64)
        if self.num_leaves <= 1:
            out[:] = self.leaf_value[0] if len(self.leaf_value) else 0.0
            return out
        dl = self.default_left()
        missing_type = (self.decision_type.astype(np.int32) >> _MISSING_TYPE_SHIFT) & 3
        for i in range(n):
            node = 0
            while node >= 0:
                f = self.split_feature[node]
                v = x[i, f]
                mt = missing_type[node]
                if np.isnan(v) and mt == 2:
                    left = dl[node]
                elif mt == 1 and (np.isnan(v) or abs(v) <= 1e-35):
                    left = dl[node]
                else:
                    vv = 0.0 if np.isnan(v) else v
                    left = vv <= self.threshold[node]
                node = self.left_child[node] if left else self.right_child[node]
            out[i] = self.leaf_value[-node - 1]
        return out

    def predict_leaf(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        n = x.shape[0]
        out = np.zeros(n, dtype=np.int32)
        if self.num_leaves <= 1:
            return out
        dl = self.default_left()
        for i in range(n):
            node = 0
            while node >= 0:
                f = self.split_feature[node]
                v = x[i, f]
                left = dl[node] if np.isnan(v) else (v <= self.threshold[node])
                node = self.left_child[node] if left else self.right_child[node]
            out[i] = -node - 1
        return out

    # ------------------------------------------------------------------
    # LightGBM text model format (reference: Tree::ToString in tree.cpp)
    # ------------------------------------------------------------------
    def to_string(self, tree_idx: int) -> str:
        m = self.num_internal
        lines = [f"Tree={tree_idx}"]
        lines.append(f"num_leaves={self.num_leaves}")
        lines.append(f"num_cat={self.num_cat}")
        lines.append("split_feature=" + _join_arr(self.split_feature[:m], "{:d}"))
        lines.append("split_gain=" + _join_arr(self.split_gain[:m], "{:g}"))
        lines.append("threshold=" + _join_arr(self.threshold[:m], "{:.17g}"))
        lines.append("decision_type=" + _join_arr(self.decision_type[:m], "{:d}"))
        lines.append("left_child=" + _join_arr(self.left_child[:m], "{:d}"))
        lines.append("right_child=" + _join_arr(self.right_child[:m], "{:d}"))
        lines.append(
            "leaf_value=" + _join_arr(self.leaf_value[: self.num_leaves], "{:.17g}")
        )
        lines.append(
            "leaf_weight=" + _join_arr(self.leaf_weight[: self.num_leaves], "{:g}")
        )
        lines.append("leaf_count=" + _join_arr(self.leaf_count[: self.num_leaves], "{:d}"))
        lines.append("internal_value=" + _join_arr(self.internal_value[:m], "{:g}"))
        lines.append("internal_weight=" + _join_arr(self.internal_weight[:m], "{:g}"))
        lines.append("internal_count=" + _join_arr(self.internal_count[:m], "{:d}"))
        if self.num_cat > 0:
            lines.append("cat_boundaries=" + _join_arr(self.cat_boundaries, "{:d}"))
            lines.append("cat_threshold=" + _join_arr(self.cat_threshold, "{:d}"))
        lines.append(f"is_linear={int(self.is_linear)}")
        lines.append(f"shrinkage={self.shrinkage:g}")
        lines.append("")
        return "\n".join(lines)

    @classmethod
    def from_string(cls, block: str) -> "Tree":
        kv = {}
        for line in block.strip().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        num_leaves = int(kv["num_leaves"])
        m = max(num_leaves - 1, 0)

        def parse_list(key, dtype, n):
            s = kv.get(key, "")
            if not s:
                return np.zeros(n, dtype=dtype)
            return np.asarray([float(t) for t in s.split()], dtype=dtype)

        num_cat = int(kv.get("num_cat", 0))
        tree = cls(
            num_leaves=num_leaves,
            split_feature=parse_list("split_feature", np.int32, m),
            threshold=parse_list("threshold", np.float64, m),
            # loaded models carry real-valued thresholds only; bin-space
            # thresholds are reconstructed lazily against a binner when the
            # tree is replayed on binned data (Dataset.predict_leaf_binned_tree)
            threshold_bin=None,
            decision_type=parse_list("decision_type", np.float64, m).astype(np.uint8),
            split_gain=parse_list("split_gain", np.float32, m),
            left_child=parse_list("left_child", np.int32, m),
            right_child=parse_list("right_child", np.int32, m),
            internal_value=parse_list("internal_value", np.float64, m),
            internal_weight=parse_list("internal_weight", np.float64, m),
            internal_count=parse_list("internal_count", np.float64, m).astype(np.int64),
            leaf_value=parse_list("leaf_value", np.float64, num_leaves),
            leaf_weight=parse_list("leaf_weight", np.float64, num_leaves),
            leaf_count=parse_list("leaf_count", np.float64, num_leaves).astype(np.int64),
            shrinkage=float(kv.get("shrinkage", 1.0)),
            num_cat=num_cat,
            is_linear=bool(int(kv.get("is_linear", 0))),
        )
        if num_cat > 0:
            tree.cat_boundaries = parse_list("cat_boundaries", np.float64, num_cat + 1).astype(np.int32)
            tree.cat_threshold = parse_list("cat_threshold", np.float64, 0).astype(np.uint32)
        return tree


def _join_arr(a, fmt: str) -> str:
    return " ".join(fmt.format(v) for v in np.asarray(a).tolist())


def tree_from_device(
    arrays,  # ops.treegrow.TreeArrays (device or host)
    binner,  # binning.DatasetBinner
    missing_types: Optional[np.ndarray] = None,
) -> Tree:
    """Trim fixed-shape device TreeArrays to an exact host Tree, converting
    bin thresholds to real values via the per-feature BinMapper
    (reference: Tree::Split stores BinMapper bin uppers as thresholds)."""
    num_leaves = int(arrays.num_leaves)
    m = max(num_leaves - 1, 0)
    split_feature = np.asarray(arrays.split_feature)[:m].astype(np.int32)
    thr_bin = np.asarray(arrays.threshold_bin)[:m].astype(np.int32)
    dl = np.asarray(arrays.default_left)[:m]

    thresholds = np.zeros(m, dtype=np.float64)
    decision_type = np.zeros(m, dtype=np.uint8)
    for i in range(m):
        f = int(split_feature[i])
        mapper = binner.mappers[f]
        thresholds[i] = mapper.bin_to_threshold(int(thr_bin[i]))
        dt = 0
        if dl[i]:
            dt |= K_DEFAULT_LEFT_MASK
        dt |= (mapper.missing_type & 3) << _MISSING_TYPE_SHIFT
        decision_type[i] = dt

    return Tree(
        num_leaves=num_leaves,
        split_feature=split_feature,
        threshold=thresholds,
        threshold_bin=thr_bin,
        decision_type=decision_type,
        split_gain=np.asarray(arrays.split_gain)[:m].astype(np.float32),
        left_child=np.asarray(arrays.left_child)[:m].astype(np.int32),
        right_child=np.asarray(arrays.right_child)[:m].astype(np.int32),
        internal_value=np.asarray(arrays.internal_value)[:m].astype(np.float64),
        internal_weight=np.asarray(arrays.internal_weight)[:m].astype(np.float64),
        internal_count=np.asarray(arrays.internal_count)[:m].astype(np.int64),
        leaf_value=np.asarray(arrays.leaf_value)[:num_leaves].astype(np.float64),
        leaf_weight=np.asarray(arrays.leaf_weight)[:num_leaves].astype(np.float64),
        leaf_count=np.asarray(arrays.leaf_count)[:num_leaves].astype(np.int64),
    )
