"""Host-side tree model: trimmed arrays + LightGBM text-format serialization.

Reference: src/io/tree.cpp / include/LightGBM/tree.h (Tree::ToString,
Tree::Split recording real-valued thresholds from bin uppers) and
src/boosting/gbdt_model_text.cpp (the `.txt` model format — the interop
contract per SURVEY.md §6.4).

decision_type bitfield (reference: include/LightGBM/tree.h):
  bit 0: categorical;  bit 1: default_left;  bits 2-3: missing type
  (0 = None, 1 = Zero, 2 = NaN).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2
_MISSING_TYPE_SHIFT = 2  # reference: kMissingTypeMask >> positions


@dataclass
class Tree:
    """One decision tree in host numpy arrays (trimmed to actual size)."""

    num_leaves: int
    split_feature: np.ndarray  # (M,) i32, M = num_leaves - 1
    threshold: np.ndarray  # (M,) f64 — real-valued
    threshold_bin: Optional[np.ndarray]  # (M,) i32 binned; None for loaded models
    decision_type: np.ndarray  # (M,) u8
    split_gain: np.ndarray  # (M,) f32
    left_child: np.ndarray  # (M,) i32
    right_child: np.ndarray  # (M,) i32
    internal_value: np.ndarray  # (M,) f64
    internal_weight: np.ndarray  # (M,) f64
    internal_count: np.ndarray  # (M,) i64
    leaf_value: np.ndarray  # (L,) f64
    leaf_weight: np.ndarray  # (L,) f64
    leaf_count: np.ndarray  # (L,) i64
    shrinkage: float = 1.0
    # categorical split storage (reference: cat_boundaries_/cat_threshold_)
    num_cat: int = 0
    cat_boundaries: np.ndarray = field(default_factory=lambda: np.zeros(1, np.int32))
    cat_threshold: np.ndarray = field(default_factory=lambda: np.zeros(0, np.uint32))
    # runtime-only (not serialized): per-node bin-space left mask for binned
    # replay of categorical nodes within the training session
    cat_bin_masks: Optional[dict] = None
    is_linear: bool = False
    # linear-tree leaf models (reference: linear_tree_learner.cpp storage:
    # leaf_const_/leaf_coeff_/leaf_features_): leaf value = leaf_const +
    # sum(coeff * raw[feature]); NaN in any used feature -> leaf_value
    leaf_const: Optional[np.ndarray] = None  # (L,)
    leaf_features: Optional[list] = None  # per-leaf list of feature ids
    leaf_coeff: Optional[list] = None  # per-leaf list of coefficients

    def is_categorical_node(self) -> np.ndarray:
        return (self.decision_type & K_CATEGORICAL_MASK) != 0

    def cat_decision_left(self, node: int, value: float) -> bool:
        """reference: Tree::CategoricalDecision — value in bitset -> left;
        NaN / negative / not-found -> right."""
        if np.isnan(value):
            return False
        iv = int(value)
        if iv < 0:
            return False
        cat_idx = int(self.threshold[node])
        lo = int(self.cat_boundaries[cat_idx])
        hi = int(self.cat_boundaries[cat_idx + 1])
        word = iv // 32
        if word >= hi - lo:
            return False
        return bool((int(self.cat_threshold[lo + word]) >> (iv % 32)) & 1)

    @property
    def num_internal(self) -> int:
        return max(self.num_leaves - 1, 0)

    def default_left(self) -> np.ndarray:
        return (self.decision_type & K_DEFAULT_LEFT_MASK) != 0

    def apply_shrinkage(self, rate: float) -> None:
        """reference: Tree::Shrinkage."""
        self.leaf_value = self.leaf_value * rate
        self.internal_value = self.internal_value * rate
        if self.is_linear and self.leaf_const is not None:
            self.leaf_const = self.leaf_const * rate
            self.leaf_coeff = [np.asarray(c) * rate for c in self.leaf_coeff]
        self.shrinkage *= rate

    # ------------------------------------------------------------------
    def predict(self, x: np.ndarray) -> np.ndarray:
        """Scalar reference predict on raw values (numpy; used by tests and
        small-batch paths — the hot path is ops/predict.py on device)."""
        x = np.asarray(x, dtype=np.float64)
        n = x.shape[0]
        out = np.empty(n, dtype=np.float64)
        if self.num_leaves <= 1:
            out[:] = self.leaf_value[0] if len(self.leaf_value) else 0.0
            return out
        dl = self.default_left()
        is_cat = self.is_categorical_node()
        missing_type = (self.decision_type.astype(np.int32) >> _MISSING_TYPE_SHIFT) & 3
        for i in range(n):
            node = 0
            while node >= 0:
                f = self.split_feature[node]
                v = x[i, f]
                if is_cat[node]:
                    left = self.cat_decision_left(node, v)
                else:
                    mt = missing_type[node]
                    if np.isnan(v) and mt == 2:
                        left = dl[node]
                    elif mt == 1 and (np.isnan(v) or abs(v) <= 1e-35):
                        left = dl[node]
                    else:
                        vv = 0.0 if np.isnan(v) else v
                        left = vv <= self.threshold[node]
                node = self.left_child[node] if left else self.right_child[node]
            out[i] = self.leaf_value[-node - 1]
        return out

    def predict_leaf_batch(self, x: np.ndarray) -> np.ndarray:
        """Vectorized numpy walk over all rows at once (host fallback path for
        categorical ensembles; the numerical hot path is ops/predict.py)."""
        x = np.asarray(x, dtype=np.float64)
        n = x.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        dl = self.default_left()
        is_cat = self.is_categorical_node()
        mt = (self.decision_type.astype(np.int32) >> _MISSING_TYPE_SHIFT) & 3
        node = np.zeros(n, dtype=np.int64)
        rows = np.arange(n)
        for _ in range(2 * self.num_leaves):
            active = node >= 0
            if not active.any():
                break
            nd = np.where(active, node, 0)
            f = self.split_feature[nd]
            v = x[rows, f]
            nanv = np.isnan(v)
            use_default = ((mt[nd] == 2) & nanv) | (
                (mt[nd] == 1) & (nanv | (np.abs(v) <= 1e-35))
            )
            veff = np.where(nanv, 0.0, v)
            left = np.where(use_default, dl[nd], veff <= self.threshold[nd])
            if is_cat.any():
                iv = veff.astype(np.int64)
                cat_idx = self.threshold[nd].astype(np.int64)
                cat_idx = np.clip(cat_idx, 0, max(self.num_cat - 1, 0))
                lo = self.cat_boundaries[cat_idx].astype(np.int64)
                nw = self.cat_boundaries[cat_idx + 1].astype(np.int64) - lo
                word = iv >> 5
                in_range = (~nanv) & (iv >= 0) & (word < nw)
                widx = lo + np.clip(word, 0, None)
                widx = np.clip(widx, 0, max(len(self.cat_threshold) - 1, 0))
                bits = (
                    self.cat_threshold[widx].astype(np.int64)
                    if len(self.cat_threshold)
                    else np.zeros(n, np.int64)
                )
                left_cat = in_range & (((bits >> (iv & 31)) & 1) != 0)
                left = np.where(is_cat[nd], left_cat, left)
            nxt = np.where(left, self.left_child[nd], self.right_child[nd])
            node = np.where(active, nxt, node)
        return (-node - 1).astype(np.int32)

    def predict_batch(self, x: np.ndarray) -> np.ndarray:
        leaf = self.predict_leaf_batch(x)
        if not self.is_linear or self.leaf_const is None:
            return self.leaf_value[leaf]
        x = np.asarray(x, np.float64)
        out = np.empty(len(leaf), np.float64)
        for l in range(self.num_leaves):
            rows = leaf == l
            if not rows.any():
                continue
            feats = np.asarray(self.leaf_features[l], np.int64)
            if len(feats) == 0:
                out[rows] = self.leaf_value[l]
                continue
            vals = x[np.ix_(rows, feats)]
            ok = np.isfinite(vals).all(axis=1)
            lin = self.leaf_const[l] + vals @ np.asarray(self.leaf_coeff[l], np.float64)
            out[rows] = np.where(ok, lin, self.leaf_value[l])
        return out

    def predict_leaf_binned_batch(self, bins: np.ndarray, binner) -> np.ndarray:
        """Vectorized walk on BINNED data (host; handles categorical nodes via
        bin-space masks).  Used for valid-score replay of categorical trees."""
        bins = np.asarray(bins)
        n = bins.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        m = self.num_internal
        is_cat = self.is_categorical_node()
        dl = self.default_left()
        if self.threshold_bin is None:
            tb = np.zeros(m, np.int32)
            for i in range(m):
                if is_cat[i]:
                    continue
                f = int(self.split_feature[i])
                tb[i] = int(
                    binner.mappers[f].transform(np.asarray([self.threshold[i]]))[0]
                )
            self.threshold_bin = tb
        masks = self._bin_masks(binner) if is_cat.any() else None
        missing_bin = binner.missing_bin_per_feature
        node = np.zeros(n, dtype=np.int64)
        rows = np.arange(n)
        for _ in range(2 * self.num_leaves):
            active = node >= 0
            if not active.any():
                break
            nd = np.where(active, node, 0)
            f = self.split_feature[nd]
            v = bins[rows, f].astype(np.int64)
            is_missing = v == missing_bin[f]
            left = np.where(is_missing, dl[nd], v <= self.threshold_bin[nd])
            if masks is not None:
                left_cat = masks[nd, v]
                left = np.where(is_cat[nd], left_cat, left)
            nxt = np.where(left, self.left_child[nd], self.right_child[nd])
            node = np.where(active, nxt, node)
        return (-node - 1).astype(np.int32)

    def _bin_masks(self, binner) -> np.ndarray:
        """(M, B) bool left-masks per node in bin space; from cat_bin_masks if
        in-session, else reconstructed from the value bitsets."""
        m = self.num_internal
        B = binner.max_num_bins
        out = np.zeros((m, B), dtype=bool)
        is_cat = self.is_categorical_node()
        for i in range(m):
            if not is_cat[i]:
                continue
            if self.cat_bin_masks is not None and i in self.cat_bin_masks:
                mk = self.cat_bin_masks[i]
                out[i, : len(mk)] = mk
            else:
                mapper = binner.mappers[int(self.split_feature[i])]
                for b, cval in enumerate(mapper.categories):
                    out[i, b] = self.cat_decision_left(i, float(cval))
        return out

    def predict_leaf(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        n = x.shape[0]
        out = np.zeros(n, dtype=np.int32)
        if self.num_leaves <= 1:
            return out
        dl = self.default_left()
        is_cat = self.is_categorical_node()
        missing_type = (self.decision_type.astype(np.int32) >> _MISSING_TYPE_SHIFT) & 3
        for i in range(n):
            node = 0
            while node >= 0:
                f = self.split_feature[node]
                v = x[i, f]
                if is_cat[node]:
                    left = self.cat_decision_left(node, v)
                else:
                    mt = missing_type[node]
                    if np.isnan(v) and mt == 2:
                        left = dl[node]
                    elif mt == 1 and (np.isnan(v) or abs(v) <= 1e-35):
                        left = dl[node]
                    else:
                        vv = 0.0 if np.isnan(v) else v
                        left = vv <= self.threshold[node]
                node = self.left_child[node] if left else self.right_child[node]
            out[i] = -node - 1
        return out

    # ------------------------------------------------------------------
    # LightGBM text model format (reference: Tree::ToString in tree.cpp)
    # ------------------------------------------------------------------
    def to_string(self, tree_idx: int, precise: bool = False) -> str:
        # precise=True is the CHECKPOINT form (GBDT.save_model_to_string
        # raw_deltas): every float field round-trips exactly (.17g), so a
        # crash-resume replays bit-identical tree state.  The default
        # keeps the reference's %g widths for the stats fields — its
        # Tree::ToString prints gains/weights/internal values at 6
        # significant digits.
        g = "{:.17g}" if precise else "{:g}"
        m = self.num_internal
        lines = [f"Tree={tree_idx}"]
        lines.append(f"num_leaves={self.num_leaves}")
        lines.append(f"num_cat={self.num_cat}")
        lines.append("split_feature=" + _join_arr(self.split_feature[:m], "{:d}"))
        lines.append("split_gain=" + _join_arr(self.split_gain[:m], g))
        lines.append("threshold=" + _join_arr(self.threshold[:m], "{:.17g}"))
        lines.append("decision_type=" + _join_arr(self.decision_type[:m], "{:d}"))
        lines.append("left_child=" + _join_arr(self.left_child[:m], "{:d}"))
        lines.append("right_child=" + _join_arr(self.right_child[:m], "{:d}"))
        lines.append(
            "leaf_value=" + _join_arr(self.leaf_value[: self.num_leaves], "{:.17g}")
        )
        lines.append(
            "leaf_weight=" + _join_arr(self.leaf_weight[: self.num_leaves], g)
        )
        lines.append("leaf_count=" + _join_arr(self.leaf_count[: self.num_leaves], "{:d}"))
        lines.append("internal_value=" + _join_arr(self.internal_value[:m], g))
        lines.append("internal_weight=" + _join_arr(self.internal_weight[:m], g))
        lines.append("internal_count=" + _join_arr(self.internal_count[:m], "{:d}"))
        if self.num_cat > 0:
            lines.append("cat_boundaries=" + _join_arr(self.cat_boundaries, "{:d}"))
            lines.append("cat_threshold=" + _join_arr(self.cat_threshold, "{:d}"))
        lines.append(f"is_linear={int(self.is_linear)}")
        if self.is_linear and self.leaf_const is not None:
            L = self.num_leaves
            lines.append("leaf_const=" + _join_arr(self.leaf_const[:L], "{:.17g}"))
            lines.append(
                "num_features=" + " ".join(str(len(self.leaf_features[l])) for l in range(L))
            )
            flat_f = [str(int(v)) for l in range(L) for v in self.leaf_features[l]]
            flat_c = ["{:.17g}".format(float(v)) for l in range(L) for v in self.leaf_coeff[l]]
            lines.append("leaf_features=" + " ".join(flat_f))
            lines.append("leaf_coeff=" + " ".join(flat_c))
        lines.append("shrinkage=" + g.format(self.shrinkage))
        lines.append("")
        return "\n".join(lines)

    @classmethod
    def from_string(cls, block: str) -> "Tree":
        kv = {}
        for line in block.strip().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        num_leaves = int(kv["num_leaves"])
        m = max(num_leaves - 1, 0)

        def parse_list(key, dtype, n):
            s = kv.get(key, "")
            if not s:
                return np.zeros(n, dtype=dtype)
            return np.asarray([float(t) for t in s.split()], dtype=dtype)

        num_cat = int(kv.get("num_cat", 0))
        tree = cls(
            num_leaves=num_leaves,
            split_feature=parse_list("split_feature", np.int32, m),
            threshold=parse_list("threshold", np.float64, m),
            # loaded models carry real-valued thresholds only; bin-space
            # thresholds are reconstructed lazily against a binner when the
            # tree is replayed on binned data (Dataset.predict_leaf_binned_tree)
            threshold_bin=None,
            decision_type=parse_list("decision_type", np.float64, m).astype(np.uint8),
            split_gain=parse_list("split_gain", np.float32, m),
            left_child=parse_list("left_child", np.int32, m),
            right_child=parse_list("right_child", np.int32, m),
            internal_value=parse_list("internal_value", np.float64, m),
            internal_weight=parse_list("internal_weight", np.float64, m),
            internal_count=parse_list("internal_count", np.float64, m).astype(np.int64),
            leaf_value=parse_list("leaf_value", np.float64, num_leaves),
            leaf_weight=parse_list("leaf_weight", np.float64, num_leaves),
            leaf_count=parse_list("leaf_count", np.float64, num_leaves).astype(np.int64),
            shrinkage=float(kv.get("shrinkage", 1.0)),
            num_cat=num_cat,
            is_linear=bool(int(kv.get("is_linear", 0))),
        )
        if num_cat > 0:
            tree.cat_boundaries = parse_list("cat_boundaries", np.float64, num_cat + 1).astype(np.int32)
            tree.cat_threshold = parse_list("cat_threshold", np.float64, 0).astype(np.uint32)
        if tree.is_linear and "leaf_const" in kv:
            tree.leaf_const = parse_list("leaf_const", np.float64, num_leaves)
            counts = parse_list("num_features", np.float64, num_leaves).astype(np.int64)
            flat_f = parse_list("leaf_features", np.float64, 0).astype(np.int64)
            flat_c = parse_list("leaf_coeff", np.float64, 0)
            tree.leaf_features, tree.leaf_coeff = [], []
            pos = 0
            for l in range(num_leaves):
                c = int(counts[l]) if l < len(counts) else 0
                tree.leaf_features.append(flat_f[pos:pos + c])
                tree.leaf_coeff.append(flat_c[pos:pos + c])
                pos += c
        return tree


def _join_arr(a, fmt: str) -> str:
    return " ".join(fmt.format(v) for v in np.asarray(a).tolist())


def tree_from_device(
    arrays,  # ops.treegrow.TreeArrays (device or host)
    binner,  # binning.DatasetBinner
    missing_types: Optional[np.ndarray] = None,
    linear=None,  # (coef (L,K), const (L,), feat_idx (L,K), nfeat (L,))
) -> Tree:
    """Trim fixed-shape device TreeArrays to an exact host Tree, converting
    bin thresholds to real values via the per-feature BinMapper
    (reference: Tree::Split stores BinMapper bin uppers as thresholds)."""
    num_leaves = int(arrays.num_leaves)
    m = max(num_leaves - 1, 0)
    split_feature = np.asarray(arrays.split_feature)[:m].astype(np.int32)
    thr_bin = np.asarray(arrays.threshold_bin)[:m].astype(np.int32)
    dl = np.asarray(arrays.default_left)[:m]
    node_is_cat = (
        np.asarray(arrays.is_cat)[:m]
        if getattr(arrays, "is_cat", None) is not None
        else np.zeros(m, bool)
    )
    node_cat_mask = (
        np.asarray(arrays.cat_mask)[:m] if node_is_cat.any() else None
    )

    thresholds = np.zeros(m, dtype=np.float64)
    decision_type = np.zeros(m, dtype=np.uint8)
    num_cat = 0
    cat_boundaries = [0]
    cat_words: list = []
    cat_bin_masks = {} if node_is_cat.any() else None
    for i in range(m):
        f = int(split_feature[i])
        mapper = binner.mappers[f]
        dt = 0
        if node_is_cat[i]:
            # bin mask -> LightGBM value bitset (reference: Tree::SplitCategorical
            # storing cat_boundaries_/cat_threshold_ over raw category values)
            mask = node_cat_mask[i]
            cat_bin_masks[i] = mask.copy()
            values = mapper.categories[
                np.flatnonzero(mask[: len(mapper.categories)])
            ].astype(np.int64)
            n_words = int(values.max() // 32 + 1) if len(values) else 1
            words = np.zeros(n_words, dtype=np.uint32)
            for v in values:
                if v >= 0:
                    words[v // 32] |= np.uint32(1) << np.uint32(v % 32)
            thresholds[i] = float(num_cat)  # cat idx
            cat_boundaries.append(cat_boundaries[-1] + n_words)
            cat_words.append(words)
            num_cat += 1
            dt |= K_CATEGORICAL_MASK
        else:
            thresholds[i] = mapper.bin_to_threshold(int(thr_bin[i]))
            if dl[i]:
                dt |= K_DEFAULT_LEFT_MASK
            dt |= (mapper.missing_type & 3) << _MISSING_TYPE_SHIFT
        decision_type[i] = dt

    return Tree(
        num_cat=num_cat,
        cat_boundaries=np.asarray(cat_boundaries, np.int32),
        cat_threshold=(
            np.concatenate(cat_words).astype(np.uint32)
            if cat_words
            else np.zeros(0, np.uint32)
        ),
        cat_bin_masks=cat_bin_masks,
        num_leaves=num_leaves,
        split_feature=split_feature,
        threshold=thresholds,
        threshold_bin=thr_bin,
        decision_type=decision_type,
        split_gain=np.asarray(arrays.split_gain)[:m].astype(np.float32),
        left_child=np.asarray(arrays.left_child)[:m].astype(np.int32),
        right_child=np.asarray(arrays.right_child)[:m].astype(np.int32),
        internal_value=np.asarray(arrays.internal_value)[:m].astype(np.float64),
        internal_weight=np.asarray(arrays.internal_weight)[:m].astype(np.float64),
        internal_count=np.asarray(arrays.internal_count)[:m].astype(np.int64),
        leaf_value=np.asarray(arrays.leaf_value)[:num_leaves].astype(np.float64),
        leaf_weight=np.asarray(arrays.leaf_weight)[:num_leaves].astype(np.float64),
        leaf_count=np.asarray(arrays.leaf_count)[:num_leaves].astype(np.int64),
        **_linear_fields(linear, num_leaves),
    )


def _linear_fields(linear, num_leaves: int) -> dict:
    if linear is None:
        return {}
    coef, const, fidx, nfeat = (np.asarray(a) for a in linear)
    return dict(
        is_linear=True,
        leaf_const=const[:num_leaves].astype(np.float64),
        leaf_features=[
            fidx[l, : int(nfeat[l])].astype(np.int64) for l in range(num_leaves)
        ],
        leaf_coeff=[
            coef[l, : int(nfeat[l])].astype(np.float64) for l in range(num_leaves)
        ],
    )


def tree_to_if_else(tree: "Tree", idx: int) -> str:
    """Emit a standalone C++ predict function for one tree
    (reference: Tree::ToIfElse in src/io/tree.cpp, task=convert_model)."""
    lines = [f"double PredictTree{idx}(const double* x) {{"]
    is_cat = tree.is_categorical_node()
    dl = tree.default_left()
    mt = (tree.decision_type.astype(np.int32) >> _MISSING_TYPE_SHIFT) & 3

    def emit(node: int, indent: int) -> None:
        pad = "  " * indent
        if node < 0:
            l = -node - 1
            if tree.is_linear and tree.leaf_const is not None:
                feats = list(np.asarray(tree.leaf_features[l], np.int64))
                if feats:
                    nan_chk = " || ".join(f"std::isnan(x[{fi}])" for fi in feats)
                    terms = " + ".join(
                        f"{float(c):.17g} * x[{fi}]"
                        for fi, c in zip(feats, np.asarray(tree.leaf_coeff[l]))
                    )
                    lines.append(
                        f"{pad}return ({nan_chk}) ? {tree.leaf_value[l]:.17g} : "
                        f"({tree.leaf_const[l]:.17g} + {terms});"
                    )
                    return
                lines.append(f"{pad}return {tree.leaf_value[l]:.17g};")
                return
            lines.append(f"{pad}return {tree.leaf_value[-node - 1]:.17g};")
            return
        f = int(tree.split_feature[node])
        if is_cat[node]:
            cat_idx = int(tree.threshold[node])
            lo = int(tree.cat_boundaries[cat_idx])
            hi = int(tree.cat_boundaries[cat_idx + 1])
            vals = []
            for w in range(lo, hi):
                word = int(tree.cat_threshold[w])
                for bit in range(32):
                    if (word >> bit) & 1:
                        vals.append((w - lo) * 32 + bit)
            conds = " || ".join(f"iv == {v}" for v in vals) or "false"
            lines.append(f"{pad}{{ const int iv = std::isnan(x[{f}]) ? -1 : (int)x[{f}];")
            lines.append(f"{pad}if ({conds}) {{")
            emit(int(tree.left_child[node]), indent + 1)
            lines.append(f"{pad}}} else {{")
            emit(int(tree.right_child[node]), indent + 1)
            lines.append(f"{pad}}} }}")
            return
        thr = float(tree.threshold[node])
        m = int(mt[node])
        v = f"x[{f}]"
        if m == 2:  # NaN routes to default
            cond_default = f"std::isnan({v})"
        elif m == 1:  # Zero (and NaN) route to default
            cond_default = f"(std::isnan({v}) || std::fabs({v}) <= 1e-35)"
        else:
            cond_default = None
        base = f"(std::isnan({v}) ? 0.0 : {v}) <= {thr:.17g}"
        if cond_default is not None:
            goes_left = f"({cond_default}) ? {str(bool(dl[node])).lower()} : ({base})"
        else:
            goes_left = base
        lines.append(f"{pad}if ({goes_left}) {{")
        emit(int(tree.left_child[node]), indent + 1)
        lines.append(f"{pad}}} else {{")
        emit(int(tree.right_child[node]), indent + 1)
        lines.append(f"{pad}}}")

    if tree.num_leaves <= 1:
        val = float(tree.leaf_value[0]) if len(np.atleast_1d(tree.leaf_value)) else 0.0
        lines.append(f"  return {val:.17g};")
    else:
        emit(0, 1)
    lines.append("}")
    return "\n".join(lines)
