"""FleetBooster: B independent boosters trained as ONE model batch.

The training-side mirror of the multi-tenant serve table (README
"Booster fleets"): per-tenant personalization wants FLEETS of small
ensembles over the SAME feature matrix — one binned dataset, B label
vectors, B independent models.  Trained as a host loop over
``engine.train`` that costs B dispatches per round plus B python
drivers; trained here it is ONE donated dispatch per round
(ops/treegrow_fleet.py::grow_fleet_windowed) plus one batched gradient
dispatch and one batched score-update dispatch per boosting iteration,
at ANY B.

Parity bar (tests/test_fleet_train.py): every lane of the fleet is
BITWISE identical to the same model trained alone through the
single-model windowed grower — float and int8-quantized.  The batched
gradient/update jits reproduce the solo iteration's op sequence
elementwise over the (B, N) plane (the allowlisted objectives are
elementwise in score/label, so broadcasting IS the solo computation),
and the grower itself vmaps the solo round body (see the fleet op's
module docstring for the W-schedule argument).

Early stop is DEVICE-SIDE: per-lane round budgets fold into the row
mask inside the batched gradient jit (``rounds > it``), so a finished
lane rides as a no-op lane — single-leaf tree, -0.0 root leaf, bitwise
score passthrough — and the host loop never branches per lane.  Budget
trees past a lane's horizon are simply not materialized.

Serving: each lane is a `_FleetLane` — a GBDT whose host trees
materialize lazily out of the fleet's STACKED device storage (one
``np.asarray`` per iteration for the whole fleet, numpy lane views
after that) and lower into the standard ``_packed`` serve layout.  Lane
packs mint their lock through the locktrace factories and join the
``_pack_version`` invalidation protocol (PR 16 discipline), so
fleet-trained models serve through ``ServingRuntime`` unchanged.

Envelope (gated loudly in ``_check_envelope``): the fused windowed
grower's single-device numerical envelope with k=1 elementwise
objectives — no bagging/GOSS, no feature sampling, no categorical
features, no EFB, no monotone/interaction/forced constraints, no
linear leaves, no ranking, no multiclass.  Everything outside belongs
to a solo ``engine.train`` run; jaxlint R18 flags the host-loop
anti-pattern the other direction.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..objectives import Objective, create_objective
from ..obs import metrics as _obs
from ..ops.treegrow import TreeArrays
from ..ops.treegrow_fleet import grow_fleet_windowed
from ..utils import locktrace as _lt
from ..utils import sanitizer as _san
from ..utils.log import set_verbosity
from .gbdt import GBDT
from .tree import Tree, tree_from_device

# objectives whose gradients are elementwise in (score, label) and carry
# no per-lane traced state beyond BinaryLogloss.pos_weight (folded to a
# (B, 1) broadcast below) — the set the batched gradient jit can serve
# bitwise-identically to B solo calls
_FLEET_OBJECTIVES = (
    "RegressionL2", "RegressionHuber", "RegressionFair",
    "RegressionPoisson", "RegressionGamma", "RegressionTweedie",
    "BinaryLogloss", "CrossEntropy",
)


class FleetError(ValueError):
    """A configuration outside the fleet envelope (module docstring)."""


def _check_envelope(cfg: Config, objective: Objective, proto: GBDT,
                    train_set) -> None:
    bad: List[str] = []
    if cfg.num_tree_per_iteration != 1:
        bad.append("multiclass objectives (num_tree_per_iteration > 1)")
    if type(objective).__name__ not in _FLEET_OBJECTIVES:
        bad.append(f"objective {cfg.objective!r} (fleet gradients must be "
                   "elementwise; supported: regression/huber/fair/poisson/"
                   "gamma/tweedie/binary/cross_entropy)")
    if getattr(objective, "need_renew", False):
        bad.append(f"objective {cfg.objective!r} needs leaf renewal")
    if proto.average_output or cfg.boosting not in ("gbdt",):
        bad.append(f"boosting={cfg.boosting!r} (gbdt only)")
    if cfg.data_sample_strategy == "goss":
        bad.append("GOSS sampling")
    if cfg.bagging_freq > 0 and (cfg.bagging_fraction < 1.0
                                 or cfg.pos_bagging_fraction < 1.0
                                 or cfg.neg_bagging_fraction < 1.0):
        bad.append("bagging")
    if cfg.feature_fraction < 1.0 or cfg.feature_fraction_bynode < 1.0 \
            or proto._needs_node_rng:
        bad.append("feature sampling / extra_trees")
    if proto._monotone is not None:
        bad.append("monotone constraints")
    if proto._interaction_sets is not None:
        bad.append("interaction constraints")
    if proto._forced_schedule() is not None:
        bad.append("forced splits")
    if proto._linear:
        bad.append("linear trees")
    if proto._categorical_mask is not None:
        bad.append("categorical features")
    if getattr(train_set, "efb", None) is not None:
        bad.append("EFB bundles")
    if cfg.num_machines > 1 or jax.process_count() > 1:
        bad.append("multi-host runs")
    if proto._cegb_lazy is not None or proto._cegb_coupled is not None:
        bad.append("CEGB penalties")
    if bad:
        raise FleetError(
            "train_fleet: configuration outside the fleet envelope — "
            + "; ".join(bad)
            + ". Train these models through engine.train instead "
            "(models/fleet.py module docstring).")


class FleetBooster:
    """B independent k=1 boosters over one shared binned dataset.

    ``labels`` is (B, N); ``weights`` optionally (B, N).  ``rounds``
    optionally gives PER-LANE boosting-round budgets (device-side early
    stop); lanes past their budget ride as no-op lanes.  Call
    :meth:`train` once, then :meth:`booster` / :meth:`boosters` for
    standard per-lane ``Booster`` handles (predict/save/serve/refit).
    """

    def __init__(self, train_set, labels, params=None, *,
                 weights=None, rounds: Optional[Sequence[int]] = None):
        self.params = dict(params or {})
        self.cfg = Config.from_dict(dict(self.params))
        set_verbosity(self.cfg.verbosity)
        labels = np.asarray(labels, np.float64)
        if labels.ndim != 2 or labels.shape[0] < 1:
            raise FleetError(
                f"train_fleet: labels must be (B, N), got {labels.shape}")
        self.fleet_size, n = labels.shape
        if self.cfg.fleet_size and self.cfg.fleet_size != self.fleet_size:
            raise FleetError(
                f"train_fleet: fleet_size={self.cfg.fleet_size} does not "
                f"match labels.shape[0]={self.fleet_size}")
        self._labels = labels
        self._weights = None
        if weights is not None:
            self._weights = np.asarray(weights, np.float64)
            if self._weights.shape != labels.shape:
                raise FleetError(
                    f"train_fleet: weights must match labels {labels.shape},"
                    f" got {self._weights.shape}")

        # lane 0's label/weight become the shared Dataset's so the proto
        # GBDT below prepares/boosts lane 0 through the EXACT solo path
        train_set.set_field("label", labels[0])
        if self._weights is not None:
            train_set.set_field("weight", self._weights[0])
        self._objectives = [create_objective(self.cfg)
                            for _ in range(self.fleet_size)]
        # the prototype solo model: constructs the dataset, and derives
        # every shared training input exactly as a solo run would —
        # _split_params, _allowed_features (feature_pre_filter), leaf
        # tile, lane 0's objective.prepare + boost_from_average init
        self._proto = GBDT(self.cfg, train_set, objective=self._objectives[0])
        self.train_set = train_set
        self.binner = self._proto.binner
        self.feature_names = list(self._proto.feature_names)
        if train_set.num_data() != n:
            raise FleetError(
                f"train_fleet: labels are (B, {n}) but the dataset has "
                f"{train_set.num_data()} rows")
        _check_envelope(self.cfg, self._objectives[0], self._proto, train_set)

        # per-lane objective state + init scores through the solo host
        # math (bitwise vs a solo run's reset_training_data); lane 0 is
        # already done by the proto's reset
        self.init_scores = [0.0] * self.fleet_size
        if self.cfg.boost_from_average:
            self.init_scores[0] = float(self._proto.init_scores[0])
        for b in range(1, self.fleet_size):
            obj = self._objectives[b]
            wb = None if self._weights is None else self._weights[b]
            if hasattr(obj, "prepare"):
                obj.prepare(labels[b], wb)
            if self.cfg.boost_from_average:
                self.init_scores[b] = float(obj.boost_from_score(
                    jnp.asarray(labels[b], jnp.float32),
                    None if wb is None else jnp.asarray(wb, jnp.float32)))

        init = np.zeros((self.fleet_size, n), np.float32)
        init += np.asarray(self.init_scores, np.float32)[:, None]
        self._score = jnp.asarray(init)
        self._bad = jnp.zeros((self.fleet_size,), jnp.int32)

        self._label_d = jnp.asarray(labels, jnp.float32)
        self._weight_d = (None if self._weights is None
                          else jnp.asarray(self._weights, jnp.float32))
        if rounds is None:
            self._rounds = None  # filled by train()
        else:
            self._rounds = np.asarray(rounds, np.int64)
            if self._rounds.shape != (self.fleet_size,) \
                    or (self._rounds < 0).any():
                raise FleetError(
                    "train_fleet: rounds must be B non-negative per-lane "
                    f"budgets, got {rounds!r}")

        # the gradient objective the batched jit traces: a fresh instance
        # whose only per-lane state (BinaryLogloss is_unbalance pos
        # weight) is folded to a (B, 1) device constant — the broadcast
        # against (B, N) reproduces each lane's solo f32 arithmetic
        self._grad_obj = create_objective(self.cfg)
        pw = np.asarray([float(getattr(o, "pos_weight", 1.0))
                         for o in self._objectives], np.float32)
        if (pw != 1.0).any():
            self._grad_obj.pos_weight = jnp.asarray(pw)[:, None]

        self._iters: List[tuple] = []  # [(stacked TreeArrays, shrinkage)]
        self._host_cache: dict = {}  # iteration -> host (np) TreeArrays
        self._lanes: dict = {}  # lane -> _FleetLane
        self._prep = None
        self._update = None
        self._trained = False

    # -- batched per-iteration jits ------------------------------------
    def _build_jits(self, rounds_d: jnp.ndarray):
        gobj, label_d, weight_d = self._grad_obj, self._label_d, self._weight_d

        @jax.jit
        # jaxlint: disable=R2 (built ONCE per fleet: train() is once-only and caches self._prep)
        def prep(score, it):
            # the solo iteration's gradient call, elementwise over (B, N);
            # per-lane budgets fold into the row mask HERE (device-side
            # early stop: a masked lane admits nothing downstream)
            g, h = gobj.get_gradients(score, label_d, weight_d)
            active = rounds_d > it
            rm = jnp.broadcast_to(active[:, None], g.shape)
            return g, h, rm

        @jax.jit
        # jaxlint: disable=R2 (built ONCE per fleet: train() is once-only and caches self._update)
        def update(score, bad, lv_b, sg_b, lid_b, shrink, it):
            # solo: score + (leaf_value * f32(shrinkage))[leaf_id], per
            # lane via one take_along_axis; the per-lane non-finite guard
            # (gbdt.py::_guard_accumulate) rides the same dispatch
            delta = jnp.take_along_axis(lv_b * shrink, lid_b, axis=1)
            ok = (jnp.isfinite(lv_b).all(axis=1)
                  & ~jnp.isnan(sg_b).any(axis=1))
            bad = jnp.where((bad == 0) & ~ok, it + 1, bad)
            return score + delta, bad

        self._prep, self._update = prep, update

    # -- training ------------------------------------------------------
    def train(self, num_boost_round: int = 100) -> "FleetBooster":
        """Run the whole fleet ``num_boost_round`` iterations (lanes with
        a smaller per-lane budget stop early ON DEVICE).  One call per
        fleet; lanes are immutable afterwards."""
        if self._trained:
            raise FleetError("train_fleet: a FleetBooster trains once")
        self._trained = True
        cfg, ts, proto = self.cfg, self.train_set, self._proto
        b = self.fleet_size
        if self._rounds is None:
            self._rounds = np.full((b,), int(num_boost_round), np.int64)
        num_boost_round = int(max(self._rounds.max(), 0))
        rounds_d = jnp.asarray(self._rounds, jnp.int32)
        self._build_jits(rounds_d)

        telemetry_on = (bool(cfg.telemetry) if cfg.is_set("telemetry")
                        else _obs.DEFAULT_ENABLED)
        _obs.set_enabled(telemetry_on)
        _obs.gauge("fleet_models").set(float(b))
        _obs.counter("train_fleet_models_total").inc(b)

        n = ts.num_data()
        bins_t = ts.bins_device_t()
        sample_weight = jnp.ones((b, n), jnp.float32)
        feature_mask = proto._allowed_features
        quant = bool(cfg.use_quantized_grad)
        shrinkage = 1.0 if proto.average_output else cfg.learning_rate
        shrink_d = jnp.float32(shrinkage)
        for it in range(num_boost_round):
            t0 = time.perf_counter()
            c0 = _san.compile_totals()["compiles"]
            g, h, rm = self._prep(self._score, jnp.int32(it))
            stats: dict = {}
            arrays_b, lid_b = grow_fleet_windowed(
                bins_t, g, h, rm, sample_weight, feature_mask,
                ts.num_bins_pf_device, ts.missing_bin_pf_device,
                (jax.random.PRNGKey(cfg.seed * 1000003 + it * 31)
                 if quant else None),
                num_leaves=cfg.num_leaves,
                num_bins=ts.max_num_bins,
                max_depth=cfg.max_depth,
                params=proto._split_params,
                leaf_tile=proto._leaf_tile(ts),
                hist_precision=cfg.hist_precision,
                use_pallas=proto._on_tpu,
                quantize_bins=(cfg.num_grad_quant_bins if quant else 0),
                stochastic_rounding=bool(cfg.stochastic_rounding),
                quant_renew=bool(cfg.quant_train_renew_leaf),
                stats=stats,
                guard_label=f" (fleet iteration {it + 1})")
            self._score, self._bad = self._update(
                self._score, self._bad, arrays_b.leaf_value,
                arrays_b.split_gain, lid_b, shrink_d, jnp.int32(it))
            self._iters.append((arrays_b, shrinkage))
            _obs.event(
                "fleet_round", models=b, iteration=it + 1,
                rounds=stats.get("rounds"),
                dispatches=stats.get("dispatches"),
                host_syncs=stats.get("host_syncs"),
                retries=stats.get("retries"),
                compiles=_san.compile_totals()["compiles"] - c0,
                ms=round((time.perf_counter() - t0) * 1e3, 3))
        return self

    # -- guard + materialization ---------------------------------------
    def _guard_check(self) -> None:
        bad = np.asarray(self._bad)
        if bad.any():
            from ..utils.guards import NonFiniteError

            lanes = np.nonzero(bad)[0].tolist()
            _obs.counter("train_nonfinite_errors_total").inc()
            _obs.event("nonfinite", phase="fleet_guard",
                       lanes=lanes[:16], iteration=int(bad[bad > 0].min()))
            raise NonFiniteError(
                f"non-finite leaf values entered fleet lane(s) {lanes[:16]} "
                f"at boosting iteration {int(bad[bad > 0].min())}; retrain "
                "the named lanes solo to isolate the offending labels "
                "(docs/ROBUSTNESS.md)")

    def _host_iter(self, i: int) -> TreeArrays:
        """Host view of iteration ``i``'s STACKED trees — one device pull
        for all B lanes, numpy slices per lane after that."""
        cached = self._host_cache.get(i)
        if cached is None:
            arrays_b = self._iters[i][0]
            cached = TreeArrays(*(None if x is None else np.asarray(x)
                                  for x in arrays_b))
            self._host_cache[i] = cached
        return cached

    def _lane_trees(self, lane: int) -> List[Tree]:
        """Lane ``lane``'s host trees (budget-trimmed, shrinkage applied)
        — the solo _flush_pending path on numpy lane views."""
        self._guard_check()
        trees = []
        for i in range(min(int(self._rounds[lane]), len(self._iters))):
            ab = self._host_iter(i)
            view = TreeArrays(*(None if x is None else x[lane] for x in ab))
            tree = tree_from_device(view, self.binner)
            tree.apply_shrinkage(self._iters[i][1])
            trees.append(tree)
        return trees

    # -- per-lane serving handles --------------------------------------
    def _lane(self, b: int) -> "_FleetLane":
        if not 0 <= b < self.fleet_size:
            raise IndexError(f"fleet lane {b} out of range "
                             f"[0, {self.fleet_size})")
        lane = self._lanes.get(b)
        if lane is None:
            lane = self._lanes[b] = _FleetLane(self, b)
        return lane

    def booster(self, b: int):
        """A standard :class:`~lightgbm_tpu.basic.Booster` over lane ``b``
        (predict / save_model / ServingRuntime / Booster.refit)."""
        from ..basic import Booster

        bst = Booster.__new__(Booster)
        bst.params = dict(self.params)
        bst.best_iteration = -1
        bst.best_score = {}
        bst._train_set = self.train_set
        bst.cfg = self.cfg
        bst._gbdt = self._lane(b)
        return bst

    def boosters(self) -> List:
        return [self.booster(b) for b in range(self.fleet_size)]

    @property
    def num_iterations(self) -> np.ndarray:
        """Per-lane trained iteration counts (the ``rounds`` budgets)."""
        return (np.zeros(self.fleet_size, np.int64) if self._rounds is None
                else self._rounds.copy())


class _FleetLane(GBDT):
    """One fleet lane as a serve/export-only GBDT: host trees materialize
    lazily from the fleet's stacked storage and flow through the standard
    ``_packed`` layout, version protocol and lock discipline — the pack
    lock is minted through the locktrace factories under its own name so
    lock-order traces attribute fleet serving correctly (PR 16)."""

    def __init__(self, fleet: FleetBooster, lane: int):
        super().__init__(fleet.cfg, None, objective=fleet._objectives[lane])
        self._fleet = fleet
        self._lane_idx = lane
        self._lane_materialized = False
        self._pack_lock = _lt.rlock("fleet.pack")
        self.binner = fleet.binner
        self.feature_names = list(fleet.feature_names)
        self.train_set = fleet.train_set
        self.init_scores = [fleet.init_scores[lane]]
        self.iter_ = min(int(fleet._rounds[lane]), len(fleet._iters))

    @property
    def models(self) -> List[Tree]:
        if not self._lane_materialized:
            self._models = self._fleet._lane_trees(self._lane_idx)
            self._lane_materialized = True
        return self._models

    @models.setter
    def models(self, value) -> None:
        self._lane_materialized = True
        GBDT.models.fset(self, value)

    def train_one_iter(self, grad=None, hess=None) -> bool:
        raise FleetError(
            "fleet lanes are serve/export-only: grow the fleet through "
            "train_fleet (continual refresh: continual_refit_leaves / "
            "fleet_refit_leaves)")
